"""Multi-process fleet transport (ISSUE 13; ROADMAP item 2(a), the
remaining leg): `ProcReplica` puts a real WORKER PROCESS behind the
PR 11 `Replica` protocol, so the `FleetRouter` fronts actual process
boundaries — a SIGKILL takes out one worker, not the fleet — without
touching a line of routing logic.

Topology: the parent spawns `python -m singa_tpu.fleet_worker` (one
per replica), which builds the SAME model from a deterministic
spec-named factory, arms the shared export-cache store, runs a
`ServingEngine`, and serves a length-prefixed CHECKSUMMED framed
protocol over a loopback socket. With the store prewarmed
(`tools/prewarm.py`, populate-once-start-N) a worker's cold start —
and every supervisor RESPAWN after a kill — is deserialize-only
(export hits >= 1, traces == 0), the PHAST portable-compiled-artifact
lesson (arxiv 2005.13076) doing the heavy lifting of the restart
story.

Robustness is the product, not a feature:

  framing      — every frame is `SF` magic + version + type + length
      + request id + a CRC32 over the payload. A torn or corrupted
      frame can NEVER be delivered as data: the reader declares the
      stream corrupt (`FrameCorruptError`), fails every in-flight
      future loudly, and kills the worker so the supervisor respawns
      it from the store — fail closed, bounded, counted
      (`torn_frames_detected`).
  IPC deadlines — every admitted request carries a transport deadline
      (`ipc_deadline_ms` + the caller's own deadline). A reply that
      does not arrive in time fails the caller's future with a
      structured `ProcTransportError` — a `ServeDispatchError`
      subclass, so the PR 11 failover path re-submits to a different
      replica unchanged. Admission itself is synchronous (REQ -> ACK),
      so submit-time refusals (shed, queue-full, overflow, closed)
      keep their exact single-engine types and the router's shed-aware
      retry fires as before.
  heartbeats   — the worker streams `HB` frames (engine `health()`
      snapshot + terminal counters + export counters) every
      `heartbeat_interval_s`. `ProcReplica.health()` returns the LAST
      heartbeat with the worker's own wall-clock stamp, so a wedged or
      dead worker's snapshot simply ages and the router's existing
      stale-snapshot ejection fires: missed heartbeat => stale =>
      fail-closed ejection, exactly the PR 11 path.
  crash detection — the reader thread sees EOF/exit, records the child
      exit code, fails every in-flight future (`ProcTransportError` =>
      failover), and flips `killed` so the router supervisor respawns
      the worker, bounded by `max_restarts`.
  backpressure — the parent bounds in-flight requests per worker
      (`max_inflight`); past it, submit sheds with a structured
      `ServeOverloadError.retry_after_ms` (the worker's own hint from
      its last heartbeat) instead of ballooning the pipe.
  reconciliation — the parent MIRRORS every IPC request into the
      process-local `cache_stats()["serve"]` terminal counters
      (exactly one terminal bucket per request), so the three PR 11
      `fleet.reconcile` equations hold across the process boundary
      unchanged; per-generation accounting (`admitted == frames +
      swept` at quiescence) plus the end-of-run handshake (the worker
      ships its final counters in the `BYE` frame; a SIGKILLed
      generation's in-flight requests are swept into `failed`) is
      checked by `fleet.reconcile_transport` — a killed-in-flight
      request lands in `failed`/failover, never vanishes.

Chaos: `resilience.FaultInjector` kinds `proc_sigkill` (a REAL
`os.kill(pid, SIGKILL)`), `proc_hang` (the worker's next dispatch
sleeps), `pipe_stall` (the parent's next frame write stalls), and
`torn_frame` (the worker corrupts its next reply frame) are keyed by
the router submit ordinal and consumed by `FleetRouter._chaos_route`.

Knobs: `device.set_fleet(transport=..., ipc_deadline_ms=...,
heartbeat_interval_s=..., spawn_timeout_s=..., max_inflight=...)`.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import export_cache
from . import slo as slo_mod
from . import trace as trace_mod
from .serve import (
    ServeClosedError,
    ServeDeadlineError,
    ServeDispatchError,
    ServeMigratedError,
    ServeOverloadError,
    ServePoisonedError,
    ServeQueueFullError,
    ServeReply,
    ServingEngine,
    note_remote_decode_export,
    note_remote_decode_session,
    note_remote_decode_terminal,
    note_remote_decode_tokens,
    note_remote_request,
    note_remote_terminal,
)

__all__ = [
    "ProcReplica",
    "ProcTransportError",
    "FrameCorruptError",
    "FrameReplayError",
    "FrameGapError",
    "encode_frame",
    "send_frame",
    "FrameReader",
    "encode_tree",
    "decode_tree",
    "decode_tree_prefix",
    "encode_req_payload",
    "decode_req_payload",
    "encode_decode_payload",
    "decode_decode_payload",
    "encode_resume_payload",
    "decode_resume_payload",
    "encode_trace_suffix",
    "decode_trace_suffix",
    "encode_error",
    "decode_error",
    "resolve_factory",
]


class ProcTransportError(ServeDispatchError):
    """The process boundary failed this request: the worker died with
    it in flight, the IPC deadline passed without a reply, or the
    frame stream went corrupt. Subclasses `ServeDispatchError` so the
    PR 11 `FleetRouter` failover path re-submits to a different
    replica unchanged — a transport failure is a fact about the
    replica, never about the input."""


class FrameCorruptError(RuntimeError):
    """A frame failed its structural checks (bad magic/version, an
    insane length, or a CRC32 mismatch): the stream cannot be trusted
    past this point. The reader fails in-flight futures loudly and
    the worker is killed/respawned — a truncated reply must never be
    delivered as data, and resyncing a corrupt byte stream would be a
    guess. On a TCP transport (ISSUE 18) the connection is torn down
    instead and the worker gets its bounded reconnect window — the
    STREAM is untrusted, not necessarily the process."""


class FrameReplayError(FrameCorruptError):
    """A frame arrived carrying a per-direction sequence number the
    receiver has ALREADY consumed: a middlebox duplicated it, or a
    stale connection replayed old bytes. Counted
    (`replay_frames_detected`) and treated as stream corruption —
    delivering it would double-deliver data, which the transport
    contract forbids."""


class FrameGapError(FrameCorruptError):
    """A frame arrived with a sequence number PAST the next expected
    ordinal: frames were reordered or silently dropped in transit
    (TCP itself never does this — a proxy, middlebox, or reconnect
    race did). Counted (`gap_frames_detected`) and treated as stream
    corruption: delivering out-of-order frames would reorder replies
    against their ACKs."""


# ---------------------------------------------------------------------------
# Wire format v2: 24-byte header + payload.
#   magic "SF" | version u8 | type u8 | payload_len u32 | req_id u64
#   | seq u32 | crc32(payload) u32
# `seq` is a per-connection, per-direction monotonic counter starting
# at 0 (ISSUE 18): a duplicated frame replays a seq the receiver has
# already consumed (`FrameReplayError`), a reordered or dropped frame
# leaves a gap (`FrameGapError`) — either way the stream is declared
# corrupt LOUDLY instead of delivering data twice or out of order. A
# reconnect is a fresh connection, so both directions restart at 0.
# ---------------------------------------------------------------------------
_MAGIC = b"SF"
_VERSION = 2
_HDR = struct.Struct(">2sBBIQII")
_MAX_PAYLOAD = 256 * 1024 * 1024  # structural sanity bound, not a knob
# Parent-side shipped-span buffer bound (per replica) + the per-frame
# piggyback bounds the worker drains into REP/HB/BYE frames. REPLY
# frames carry spans only under ship-buffer PRESSURE (>= half full):
# span bytes on the request path cost latency, so the steady-state
# carrier is the heartbeat and the reply piggyback is the relief
# valve that keeps drops bounded under bursts.
_MAX_SHIPPED = 8192
SPANS_PER_REP = 64
SPANS_PER_HB = 256
SPANS_PER_BYE = 2048

# Frame types.
HELLO = 1    # worker -> parent: {token, pid, name} (connection auth)
REQ = 2      # parent -> worker: deadline_ms f64 + encoded arrays
ACK = 3      # worker -> parent: request admitted (empty payload)
REP = 4      # worker -> parent: flags u8 (bit0 = late) + encoded tree
ERR = 5      # worker -> parent: JSON structured error (see encode_error)
HB = 6       # worker -> parent: JSON heartbeat (health+counters+export)
CTRL = 7     # parent -> worker: JSON {op, ...}
CTRL_OK = 8  # worker -> parent: JSON result for a sync CTRL/WARM
WARM = 9     # parent -> worker: encoded arrays (engine.warmup)
BYE = 10     # worker -> parent: JSON final counters (the reconciliation
             # handshake) — last frame of a clean drain/stop
# Decode-tier session frames (ISSUE 17). Byte-absent when unused: a
# fleet that never calls submit_decode puts none of these on the wire,
# and the forward-tier frame stream is byte-identical to PR 13.
DECODE = 11  # parent -> worker: decode-session params tree (+ optional
             # trace suffix) — ACKed synchronously like REQ
TOK = 12     # worker -> parent: one streamed token (i32) as its fused
             # decode step lands — feeds the parent reply's stream
MIGRATE = 13 # worker -> parent: the session's live-migration
             # checkpoint (drain path) — supersedes ERR: a migrated
             # session has no local terminal, it re-admits elsewhere
RESUME = 14  # parent -> worker: checkpoint admission (encoded ckpt
             # tree + optional trace suffix) — ACKed like DECODE
# TCP transport handshake frames (ISSUE 18). Spawn mode never puts
# these on the wire.
WELCOME = 15 # parent -> worker: JSON {fence, gen, spec?} — the
             # parent accepted this connection's HELLO; `fence` is the
             # generation-fence epoch the worker must echo on every
             # reconnect, `spec` ships only when the HELLO asked
             # (need_spec: a remotely launched worker has no env spec)
FENCED = 16  # parent -> worker: JSON {reason} — the connection's
             # HELLO carried a stale (or missing) fence: this worker
             # generation is superseded and must NOT serve; the parent
             # closes after sending. Counted stale_reconnects_refused.


def send_frame(sock, frame: bytes, deadline_s: float = 10.0) -> None:
    """Write one frame to `sock` COMPLETELY or fail — never leave a
    partial frame on the wire and return control (satellite: partial-
    write hardening). `sock.sendall` under a socket timeout can write
    a PREFIX of the frame and then raise `socket.timeout`; a retry of
    the next frame would interleave bytes mid-frame and corrupt the
    stream unrecoverably. This loop retries short writes on the SAME
    frame until `deadline_s` expires; on expiry (or any socket error
    mid-frame) it raises OSError — callers must treat the connection
    as broken, because bytes of a half-frame may already be out."""
    view = memoryview(frame)
    deadline = time.perf_counter() + deadline_s
    while view:
        try:
            sent = sock.send(view)
        except socket.timeout:
            if time.perf_counter() >= deadline:
                raise OSError(
                    f"send deadline ({deadline_s}s) expired with "
                    f"{len(view)}/{len(frame)} frame bytes unwritten: "
                    "connection is congested past tolerance") from None
            continue
        except InterruptedError:
            continue
        if sent == 0:
            raise OSError("socket connection broken mid-frame")
        view = view[sent:]


def encode_frame(ftype: int, req_id: int, payload: bytes,
                 corrupt: bool = False, seq: int = 0) -> bytes:
    """One wire frame. `corrupt=True` (the `torn_frame` chaos hook)
    flips payload bytes AFTER the CRC is computed — the receiver's
    checksum must catch it, which is the point. `seq` is the sender's
    per-connection monotonic ordinal for this direction."""
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if corrupt and payload:
        payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
    elif corrupt:
        crc ^= 0xDEADBEEF
    return _HDR.pack(_MAGIC, _VERSION, ftype, len(payload),
                     req_id, seq & 0xFFFFFFFF, crc) + payload


# Amortized-compaction tuning for FrameReader: the consumed prefix is
# only sliced off once it dominates the buffer (and is big enough to
# matter), so a slow-drip byte stream costs O(total_bytes) instead of
# the old per-frame `del buf[:k]` O(n^2) re-copy.
_COMPACT_MIN = 1 << 16


class FrameReader:
    """Incremental frame parser over a byte stream. `feed(chunk)`
    returns every COMPLETE frame the buffer now holds; a partial
    frame waits for more bytes (a short read is normal, not an
    error), but structural damage — bad magic/version, a length past
    `max_frame_bytes`, a CRC mismatch — raises `FrameCorruptError`
    immediately. With `check_seq=True` (the live transport) every
    frame's header seq must be EXACTLY the next expected ordinal:
    a replayed/duplicated frame raises `FrameReplayError`, a gap
    (reorder or loss) raises `FrameGapError` — both subclass
    `FrameCorruptError`, so every existing fail-closed path applies.

    Parsing keeps a read cursor (`_off`) into one growing buffer and
    compacts the consumed prefix AMORTIZED (only once it exceeds both
    `_COMPACT_MIN` and half the buffer): under 1-byte slow-drip
    arrival the old per-frame front-slice was quadratic in stream
    length."""

    def __init__(self, max_frame_bytes: Optional[int] = None,
                 check_seq: bool = False):
        self._buf = bytearray()
        self._off = 0
        cap = _MAX_PAYLOAD if max_frame_bytes is None \
            else int(max_frame_bytes)
        self.max_frame_bytes = min(max(cap, 1), _MAX_PAYLOAD)
        self._check_seq = bool(check_seq)
        self._expect_seq = 0

    def feed(self, chunk: bytes) -> List[Tuple[int, int, bytes]]:
        self._buf.extend(chunk)
        out: List[Tuple[int, int, bytes]] = []
        buf = self._buf
        off = self._off
        try:
            while len(buf) - off >= _HDR.size:
                magic, ver, ftype, n, rid, seq, crc = _HDR.unpack_from(
                    buf, off)
                if magic != _MAGIC or ver != _VERSION:
                    raise FrameCorruptError(
                        f"bad frame header (magic {magic!r}, version "
                        f"{ver}): stream corrupt")
                if n > self.max_frame_bytes:
                    raise FrameCorruptError(
                        f"frame claims {n} payload bytes (cap "
                        f"{self.max_frame_bytes}): refusing to buffer "
                        "it — stream corrupt")
                if len(buf) - off < _HDR.size + n:
                    break  # torn so far — wait for the rest
                payload = bytes(buf[off + _HDR.size:
                                    off + _HDR.size + n])
                off += _HDR.size + n
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise FrameCorruptError(
                        f"frame {rid} type {ftype} failed its CRC32: "
                        "a torn/corrupt reply must never be delivered "
                        "as data")
                if self._check_seq:
                    want = self._expect_seq & 0xFFFFFFFF
                    if seq != want:
                        if ((want - seq) & 0xFFFFFFFF) <= 0x7FFFFFFF:
                            raise FrameReplayError(
                                f"frame {rid} type {ftype} replays "
                                f"seq {seq} (expected {want}): a "
                                "duplicated frame must never be "
                                "delivered twice")
                        raise FrameGapError(
                            f"frame {rid} type {ftype} arrives at seq "
                            f"{seq} (expected {want}): frames were "
                            "reordered or lost in transit")
                    self._expect_seq += 1
                out.append((ftype, rid, payload))
        finally:
            self._off = off
            if off and (off == len(buf)
                        or (off > _COMPACT_MIN and off > len(buf) // 2)):
                del buf[:off]
                self._off = 0
        return out

    def pending_bytes(self) -> int:
        return len(self._buf) - self._off


# ---------------------------------------------------------------------------
# Payload codec: numpy pytrees (the serve request/reply shapes) without
# pickle — deterministic bytes, no code execution on decode.
# ---------------------------------------------------------------------------
_T_ARR, _T_LIST, _T_TUPLE, _T_DICT, _T_NONE = b"A", b"L", b"T", b"D", b"0"
_MAX_DEPTH = 16


def encode_tree(node) -> bytes:
    out: List[bytes] = []
    _enc(node, out, 0)
    return b"".join(out)


def _enc(node, out: List[bytes], depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("reply tree deeper than the wire codec's "
                         f"bound ({_MAX_DEPTH})")
    if node is None:
        out.append(_T_NONE)
        return
    if isinstance(node, (list, tuple)):
        out.append(_T_LIST if isinstance(node, list) else _T_TUPLE)
        out.append(struct.pack(">I", len(node)))
        for child in node:
            _enc(child, out, depth + 1)
        return
    if isinstance(node, dict):
        out.append(_T_DICT)
        out.append(struct.pack(">I", len(node)))
        for k in node:  # insertion order — round-trips exactly
            kb = str(k).encode("utf-8")
            out.append(struct.pack(">H", len(kb)))
            out.append(kb)
            _enc(node[k], out, depth + 1)
        return
    a = np.asarray(getattr(node, "data", node))
    # ascontiguousarray promotes 0-d to 1-d: reshape back
    a = np.ascontiguousarray(a).reshape(a.shape)
    dt = a.dtype.str.encode("ascii")
    out.append(_T_ARR)
    out.append(struct.pack(">B", len(dt)))
    out.append(dt)
    out.append(struct.pack(">B", a.ndim))
    out.append(struct.pack(f">{a.ndim}Q", *a.shape))
    raw = a.tobytes()
    out.append(struct.pack(">Q", len(raw)))
    out.append(raw)


def decode_tree(buf: bytes):
    node, off = _dec(buf, 0, 0)
    if off != len(buf):
        raise FrameCorruptError(
            f"payload has {len(buf) - off} trailing bytes after the "
            "tree: codec desync")
    return node


def decode_tree_prefix(buf: bytes, off: int = 0):
    """Decode one tree starting at `off`, returning (node, end_off) —
    for payloads that carry a structured suffix AFTER the tree (the
    optional trace block on REQ frames). Callers that expect nothing
    after the tree must check end_off themselves (`decode_tree` does
    exactly that)."""
    return _dec(buf, off, 0)


def _dec(buf: bytes, off: int, depth: int):
    if depth > _MAX_DEPTH:
        raise FrameCorruptError("wire tree deeper than the codec bound")
    tag = buf[off:off + 1]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag in (_T_LIST, _T_TUPLE):
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        items = []
        for _ in range(n):
            child, off = _dec(buf, off, depth + 1)
            items.append(child)
        return (items if tag == _T_LIST else tuple(items)), off
    if tag == _T_DICT:
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        d = {}
        for _ in range(n):
            (kl,) = struct.unpack_from(">H", buf, off)
            off += 2
            k = buf[off:off + kl].decode("utf-8")
            off += kl
            d[k], off = _dec(buf, off, depth + 1)
        return d, off
    if tag == _T_ARR:
        (dl,) = struct.unpack_from(">B", buf, off)
        off += 1
        dt = buf[off:off + dl].decode("ascii")
        off += dl
        (nd,) = struct.unpack_from(">B", buf, off)
        off += 1
        shape = struct.unpack_from(f">{nd}Q", buf, off)
        off += 8 * nd
        (rl,) = struct.unpack_from(">Q", buf, off)
        off += 8
        a = np.frombuffer(buf[off:off + rl],
                          dtype=np.dtype(dt)).reshape(shape)
        return a.copy(), off + rl
    raise FrameCorruptError(f"unknown wire tree tag {tag!r}")


# ---------------------------------------------------------------------------
# Trace context on the wire (ISSUE 15): an OPTIONAL suffix after the
# REQ frame's tree — tag "T", trace-id length+bytes, and the parent
# span id under which the worker's spans causally nest. STRICTLY
# absent when tracing is disabled: a disabled-mode REQ payload is
# byte-for-byte the pre-trace format, and the worker's ACK stays
# empty (an ACK for a TRACED request carries one f64 — the worker's
# perf_counter stamp the parent's clock-offset estimate needs).
# ---------------------------------------------------------------------------
def encode_trace_suffix(trace_id: str, parent=None) -> bytes:
    tb = str(trace_id).encode("ascii")
    if not tb or len(tb) > 255:
        raise ValueError(f"trace id length {len(tb)} not in [1, 255]")
    out = b"T" + struct.pack(">B", len(tb)) + tb
    if parent is None:
        return out + b"\x00"
    return out + b"\x01" + struct.pack(">Q", int(parent))


def decode_trace_suffix(buf: bytes, off: int):
    """(trace_id, parent) from the optional suffix at `off`; (None,
    None) when the payload ends there (untraced request). Anything
    else is structural damage."""
    if off == len(buf):
        return None, None
    if buf[off:off + 1] != b"T":
        raise FrameCorruptError(
            f"{len(buf) - off} trailing bytes after the tree that are "
            "not a trace suffix: codec desync")
    off += 1
    (n,) = struct.unpack_from(">B", buf, off)
    off += 1
    tid = buf[off:off + n].decode("ascii")
    off += n
    (has_parent,) = struct.unpack_from(">B", buf, off)
    off += 1
    parent = None
    if has_parent:
        (parent,) = struct.unpack_from(">Q", buf, off)
        off += 8
    if off != len(buf):
        raise FrameCorruptError(
            f"{len(buf) - off} trailing bytes after the trace suffix")
    return tid, parent


def encode_req_payload(deadline_ms, batch, trace=None) -> bytes:
    """One REQ payload: f64 deadline + encoded arrays (+ the trace
    suffix IFF `trace` is given — `(trace_id, parent_span_id)`). The
    zero-extra-wire-bytes contract lives here: trace=None produces
    exactly the pre-trace byte layout."""
    dl = -1.0 if deadline_ms is None else float(deadline_ms)
    payload = struct.pack(">d", dl) + encode_tree(list(batch))
    if trace is not None:
        payload += encode_trace_suffix(trace[0], trace[1])
    return payload


def decode_req_payload(payload: bytes):
    """(deadline_ms_or_None, arrays, trace_id, parent) — the worker
    side of `encode_req_payload`."""
    (dl,) = struct.unpack_from(">d", payload, 0)
    arrays, off = decode_tree_prefix(payload, 8)
    tid, parent = decode_trace_suffix(payload, off)
    return (None if dl < 0 else dl), arrays, tid, parent


def encode_decode_payload(prompt, n_new, temperature, top_k, seed,
                          deadline_ms, trace=None) -> bytes:
    """One DECODE payload: the session params as a wire tree (+ the
    trace suffix IFF `trace` is given — the REQ contract verbatim:
    an untraced session adds zero wire bytes)."""
    payload = encode_tree({
        "prompt": np.asarray(prompt, np.int32),
        "n_new": int(n_new),
        "temperature": float(temperature),
        "top_k": int(top_k),
        "seed": int(seed),
        "deadline_ms": (None if deadline_ms is None
                        else float(deadline_ms)),
    })
    if trace is not None:
        payload += encode_trace_suffix(trace[0], trace[1])
    return payload


def decode_decode_payload(payload: bytes):
    """(params_dict, trace_id, parent) — the worker side of
    `encode_decode_payload`. Scalars come back as 0-d numpy arrays
    (the tree codec's scalar form); the engine coerces."""
    d, off = decode_tree_prefix(payload, 0)
    tid, parent = decode_trace_suffix(payload, off)
    return d, tid, parent


def encode_resume_payload(ckpt: Dict, trace=None) -> bytes:
    """One RESUME payload: the migration checkpoint tree (numpy
    arrays / scalars / None leaves only — `export_decode_sessions`'s
    documented contract) + the optional trace suffix."""
    payload = encode_tree(dict(ckpt))
    if trace is not None:
        payload += encode_trace_suffix(trace[0], trace[1])
    return payload


def decode_resume_payload(payload: bytes):
    ckpt, off = decode_tree_prefix(payload, 0)
    tid, parent = decode_trace_suffix(payload, off)
    return ckpt, tid, parent


# ---------------------------------------------------------------------------
# Structured error mapping: the worker's exact single-engine exception
# types survive the boundary, so the router's failover/shed/poison
# policies fire unchanged.
# ---------------------------------------------------------------------------
def encode_error(e: BaseException) -> Dict:
    if isinstance(e, export_cache.BucketOverflowError):
        kind = "overflow"
    elif isinstance(e, ServeDeadlineError):
        kind = "deadline"
    elif isinstance(e, ServeOverloadError):
        return {"kind": "overload", "msg": str(e),
                "retry_after_ms": float(e.retry_after_ms)}
    elif isinstance(e, ServeQueueFullError):
        kind = "queue_full"
    elif isinstance(e, ServePoisonedError):
        kind = "poisoned"
    elif isinstance(e, ServeClosedError):
        return {"kind": "closed", "msg": str(e),
                "counted": bool(getattr(e, "counted", False))}
    elif isinstance(e, ServeDispatchError):
        kind = "dispatch"
    else:
        return {"kind": "dispatch", "msg": f"{type(e).__name__}: {e}"}
    return {"kind": kind, "msg": str(e)}


def decode_error(d: Dict) -> BaseException:
    kind, msg = d.get("kind", "dispatch"), d.get("msg", "")
    if kind == "overflow":
        return export_cache.BucketOverflowError(msg)
    if kind == "deadline":
        return ServeDeadlineError(msg)
    if kind == "overload":
        return ServeOverloadError(
            msg, retry_after_ms=float(d.get("retry_after_ms", 1.0)))
    if kind == "queue_full":
        return ServeQueueFullError(msg)
    if kind == "poisoned":
        return ServePoisonedError(msg)
    if kind == "closed":
        e = ServeClosedError(msg)
        if d.get("counted"):
            e.counted = True
        return e
    if kind == "transport":
        return ProcTransportError(msg)
    return ServeDispatchError(msg)


# Parent-side serve-counter bucket for each decoded terminal error.
_ERR_TERMINAL = {
    "deadline": "expired",
    "poisoned": "poisoned",
    "dispatch": "failed",
    "closed": "failed",
    "transport": "failed",
}

# Decode-SESSION mirror buckets (the 4-equation books): an admission
# refusal maps overload -> shed; an admitted session's error frame
# maps deadline -> expired; everything else is failed. `completed`
# comes from the final REP, and migration is not a terminal at all.
_DECODE_ERR_TERMINAL = {
    "deadline": "expired",
    "overload": "shed",
}


# ---------------------------------------------------------------------------
# Parent-side request bookkeeping
# ---------------------------------------------------------------------------
class _Pending:
    __slots__ = ("reply", "gen", "acked", "ack_err", "ack_ev",
                 "ipc_abs", "sweep_failed", "claimed", "trace",
                 "t_send", "decode")

    def __init__(self, reply: ServeReply, gen: int):
        self.reply = reply
        self.gen = gen
        self.acked = False
        self.ack_err: Optional[BaseException] = None
        self.ack_ev = threading.Event()
        self.ipc_abs: Optional[float] = None
        self.sweep_failed = False  # future failed, frame still owed
        self.trace = None  # (trace_id, parent) on a traced request
        self.t_send: Optional[float] = None  # REQ send perf_counter
        # decode-tier SESSION (DECODE/RESUME): terminals mirror into
        # the decode books, not the forward ones, and TOK frames feed
        # the reply's stream while the entry stays pending
        self.decode = False
        # One-terminal arbiter for UN-ADMITTED requests: the
        # submit()-timeout path, the reader's ERR-refusal path, and
        # the death sweep can all race to mirror this request's
        # terminal bucket — whoever takes the claim (under _plock)
        # mirrors, everyone else stands down. (Admitted requests are
        # arbitrated by the reply future's first write instead.)
        self.claimed = False

    def take_claim(self) -> bool:
        """Must be called under the owner's _plock."""
        if self.claimed:
            return False
        self.claimed = True
        return True


class _Gen:
    """Per-worker-generation reconciliation ledger: at quiescence
    `admitted == frames + swept + migrated` exactly — an admitted
    request either produced a reply/error frame that arrived, was
    swept into `failed` when its generation died, or (decode sessions
    only) LEFT on a MIGRATE frame to resume elsewhere. `handshake`
    holds the worker's final counters when the generation drained
    cleanly (the BYE frame); a SIGKILLed generation has none, which
    is exactly why the parent-side ledger is the authoritative one."""

    __slots__ = ("admitted", "frames", "swept", "migrated", "ack_errs",
                 "handshake", "clean", "exit_code", "pid", "clock",
                 "clock_offset_us", "clock_rtt_s", "clock_wall_us")

    def __init__(self, pid: int):
        self.admitted = 0
        self.frames = 0
        self.swept = 0
        self.migrated = 0
        self.ack_errs = 0
        self.handshake: Optional[Dict] = None
        self.clean = False
        self.exit_code: Optional[int] = None
        self.pid = pid
        # monotonic-clock alignment (ISSUE 15/18): worker
        # perf_counter + offset = parent perf_counter. Primary
        # estimate from the REQ->ACK handshake via
        # `trace.OffsetEstimator` (median over the smallest-RTT
        # samples, so network jitter and injected asymmetric delay
        # are filtered, not averaged in); fallback from the
        # heartbeat's (wall, mono) pair when no traced request has
        # round-tripped this generation yet.
        self.clock = trace_mod.OffsetEstimator()
        self.clock_offset_us: Optional[float] = None
        self.clock_rtt_s: Optional[float] = None
        self.clock_wall_us: Optional[float] = None

    def offset_us(self) -> float:
        if self.clock_offset_us is not None:
            return self.clock_offset_us
        if self.clock_wall_us is not None:
            return self.clock_wall_us
        return 0.0


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def resolve_factory(spec: Dict):
    """Import the spec's "module:callable" factory (after inserting
    its `sys_path` entries) — the one resolution both transports and
    the worker entrypoint share."""
    import importlib

    for p in spec.get("sys_path") or []:
        if p not in sys.path:
            sys.path.insert(0, p)
    mod_name, _, fn_name = str(spec.get("factory", "")).partition(":")
    if not fn_name:
        raise ValueError(
            f"spec factory {spec.get('factory')!r} must be "
            "'module:callable'")
    return getattr(importlib.import_module(mod_name), fn_name)


def _jsonable_spec(spec: Dict) -> Dict:
    """The spec crosses the boundary as JSON; FaultInjector schedules
    are documented as sets of step ordinals, which json refuses —
    normalize them (FaultInjector accepts any iterable back)."""
    out = dict(spec)
    inj = out.get("injector")
    if inj:
        inj = dict(inj)
        sched = {}
        for k, v in (inj.get("schedule") or {}).items():
            if isinstance(v, (set, frozenset, tuple)):
                v = sorted(int(s) for s in v)
            sched[k] = v
        inj["schedule"] = sched
        out["injector"] = inj
    return out


class ProcReplica:
    """A serving replica living in its OWN worker process, behind the
    exact `Replica` protocol `fleet.FleetRouter` speaks (start/kill/
    drain_stop/restart/submit/health/depth/warmup/killed + the chaos
    hooks) — the router cannot tell it from an `EngineReplica`, which
    is the whole point.

    `spec` names everything the worker needs to rebuild the replica
    deterministically (so a respawn is bit-identical and, with the
    shared store armed, deserialize-only):

      factory         "module:callable" returning a COMPILED eval-mode
                      Model (the `tools/prewarm.py --factory` idiom)
      factory_kwargs  keyword args for it (e.g. device_index, seed)
      sys_path        extra sys.path entries for the import
      engine          ServingEngine kwargs (max_batch, max_wait_ms,
                      shed_watermark, health_file, ...)
      injector        {"seed", "schedule", "hang_s"} rebuilt into a
                      worker-side `resilience.FaultInjector`
      export_cache    store dir (default: the parent's armed store —
                      the populate-once-start-N contract)
      buckets         device.set_shape_buckets kwargs for the worker
      quant           inference quant mode ("int8") armed at worker
                      boot BEFORE the engine builds (ISSUE 19) —
                      every replica of a fleet must share it so
                      MIGRATE/RESUME KV stays one form
      metrics_path    worker-side serving metrics JSONL (read it back
                      with `trace.read_metrics`; flush-per-record, so
                      a SIGKILLed worker leaves a parseable log)

    Transport modes (ISSUE 18) — the same `Replica` protocol over
    three launch/dial topologies:

      spawn    (default) today's behavior, unchanged: the parent binds
               an ephemeral loopback listener, spawns the worker with
               the spec in its env, and the connection IS the process
               — EOF means child death.
      listen   the parent binds a routable `host:port` and keeps
               accepting; the worker is launched ANYWHERE via
               `python -m singa_tpu.fleet_worker --connect host:port
               --token ...` (`launch="local"` makes the parent launch
               it locally — the hermetic test/bench arrangement;
               `launch="none"` waits for an external one). The spec
               ships over the wire in the WELCOME frame when the
               worker's HELLO asks (`need_spec`).
      connect  the parent DIALS an already-running worker started
               with `--listen host:port`.

    In the TCP modes socket EOF no longer implies child death: the
    generation gets a bounded `reconnect_window_s` during which
    in-flight requests fail over (PR 11 machinery — never hang, never
    double-deliver) and a reconnect carrying the current generation
    FENCE resumes the same generation with fresh per-direction frame
    sequence numbers; a stale fence is refused loudly (FENCED frame,
    `stale_reconnects_refused`). Window expiry flips `killed` and the
    supervisor's restart story takes over.

    Transport knobs (constructor kwargs, defaulting to the
    `device.set_fleet` process config): `ipc_deadline_ms`,
    `heartbeat_interval_s`, `spawn_timeout_s`, `max_inflight`,
    `reconnect_window_s`, `max_frame_bytes`."""

    def __init__(self, name: str, spec: Dict, *,
                 ipc_deadline_ms: Optional[float] = None,
                 heartbeat_interval_s: Optional[float] = None,
                 spawn_timeout_s: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 python: Optional[str] = None,
                 mode: str = "spawn",
                 host: str = "127.0.0.1",
                 port: int = 0,
                 launch: str = "local",
                 reconnect_window_s: Optional[float] = None,
                 max_frame_bytes: Optional[int] = None,
                 net_chaos: Optional[Dict] = None):
        from . import fleet

        cfg = fleet.get_config()
        self.name = str(name)
        self.spec = dict(spec)
        if mode not in ("spawn", "listen", "connect"):
            raise ValueError(
                f"unknown ProcReplica mode {mode!r} "
                "(spawn|listen|connect)")
        if launch not in ("local", "none"):
            raise ValueError(
                f"unknown ProcReplica launch {launch!r} (local|none)")
        self._mode = mode
        self._host = str(host)
        self._port = int(port)
        self._launch = launch if mode == "listen" else "none"
        if mode == "spawn":
            self._launch = "local"
        self.reconnect_window_s = float(
            reconnect_window_s if reconnect_window_s is not None
            else cfg.get("reconnect_window_s", 10.0))
        self.max_frame_bytes = int(
            max_frame_bytes if max_frame_bytes is not None
            else cfg.get("max_frame_bytes", _MAX_PAYLOAD))
        self._net_chaos = dict(net_chaos) if net_chaos else None
        if self._net_chaos is not None and mode != "listen":
            raise ValueError(
                "net_chaos needs mode='listen' (the proxy fronts the "
                "parent's listener)")
        if "factory" not in self.spec:
            raise ValueError(
                "ProcReplica spec needs a 'factory' (module:callable) "
                "— the worker must rebuild the model deterministically")
        self.ipc_deadline_s = float(
            ipc_deadline_ms if ipc_deadline_ms is not None
            else cfg["ipc_deadline_ms"]) / 1e3
        self.heartbeat_interval_s = float(
            heartbeat_interval_s if heartbeat_interval_s is not None
            else cfg["heartbeat_interval_s"])
        self.spawn_timeout_s = float(
            spawn_timeout_s if spawn_timeout_s is not None
            else cfg["spawn_timeout_s"])
        self.max_inflight = int(max_inflight if max_inflight is not None
                                else cfg["max_inflight"])
        if self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self._python = python or sys.executable
        self.killed = False
        self.restarts = 0
        self.engine = None  # protocol parity: no in-process engine
        self._proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._wlock = threading.Lock()
        self._plock = threading.Lock()  # pending/gen bookkeeping
        self._pending: Dict[int, _Pending] = {}
        self._ctrl_pending: Dict[int, Dict] = {}
        self._next_id = 0
        self._gen = 0
        self._gens: Dict[int, _Gen] = {}
        self._hb: Optional[Dict] = None
        self._hb_rx = 0.0
        self._frozen_snap: Optional[Dict] = None
        self._frozen_until = 0.0
        self._stall_s = 0.0
        self._draining = False
        # TCP transport state (ISSUE 18). The fence is the parent's
        # generation-epoch counter: bumped on every FRESH adoption, it
        # is handed to the worker in WELCOME and must be echoed by
        # every reconnect HELLO — a stale/replayed connection carries
        # yesterday's fence and is refused, so a superseded worker can
        # never resurrect its generation. The token is stable for the
        # replica's lifetime in TCP modes (a remotely launched worker
        # cannot learn a fresh one per spawn).
        import secrets

        self._fence = 0
        self._tx_seq = 0
        self._token = str(self.spec.get("token")
                          or secrets.token_hex(16))
        self._lsock: Optional[socket.socket] = None
        self._listen_addr: Optional[Tuple[str, int]] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._proxy = None  # netchaos.ChaosProxy when net_chaos armed
        self._proxy_final = None  # last snapshot, kept across stop()
        self._superseded: set = set()  # old socks a reconnect replaced
        self._reconnecting = False
        self._reconnect_deadline = 0.0
        self._established = threading.Event()
        # lifetime transport counters (reconcile_transport reads them)
        self.sent = 0
        self.delivered = 0
        self.err_replies = 0
        self.transport_failed = 0
        self.torn_frames_detected = 0
        self.replay_frames_detected = 0
        self.gap_frames_detected = 0
        self.ipc_timeouts = 0
        self.hb_received = 0
        self.reconnects = 0
        self.reconnect_windows = 0
        self.stale_reconnects_refused = 0
        # decode-tier lane (ISSUE 17): its own sent/terminal counters
        # so the forward parent-terminals equation is untouched; at
        # quiescence decode_sent == decode_delivered +
        # decode_err_replies + decode_transport_failed + migrated_out.
        self.decode_sent = 0
        self.decode_delivered = 0
        self.decode_err_replies = 0
        self.decode_transport_failed = 0
        self.migrated_out = 0
        self.decode_tokens = 0
        # shipped worker spans (ISSUE 15): raw worker-clock records
        # piggybacked on REP/HB/BYE frames, kept per generation for
        # `trace_source()` to hand `trace.merge_chrome_traces` with
        # that generation's clock offset. Bounded deque; overflow
        # drops the OLDEST and counts it (O(1) — a list.pop(0) here
        # would memmove 8k entries under _plock on the reader's hot
        # path once full).
        from collections import deque

        self._shipped: "deque" = deque()
        self.spans_received = 0
        self.spans_dropped = 0

    # -- lifecycle --------------------------------------------------------
    @property
    def _tcp(self) -> bool:
        return self._mode != "spawn"

    def start(self) -> "ProcReplica":
        if self._mode == "listen":
            return self._start_listen()
        if self._mode == "connect":
            return self._start_connect()
        return self._start_spawn()

    def _start_spawn(self) -> "ProcReplica":
        if self._proc is not None and self._proc.poll() is None:
            self.killed = False
            return self
        import secrets

        token = secrets.token_hex(16)
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            lsock.bind(("127.0.0.1", 0))
            lsock.listen(1)
            port = lsock.getsockname()[1]
            spec = _jsonable_spec(self.spec)
            spec.setdefault("name", self.name)
            spec["port"] = port
            spec["token"] = token
            spec["heartbeat_interval_s"] = self.heartbeat_interval_s
            if trace_mod.enabled():
                # arm the worker's tracer + span ship-back at spawn —
                # and at every supervisor RESPAWN, since restart()
                # re-enters here: a new generation keeps propagating
                # the same trace contexts. (An explicit spec "trace"
                # wins — tests pin tiny ship buffers through it.)
                spec.setdefault("trace", {
                    "enabled": True, "ship_capacity": 2048,
                    "ring_capacity":
                        trace_mod.get_config()["ring_capacity"]})
            if "export_cache" not in spec:
                # inherit the parent's armed store: the populate-
                # once-start-N contract — a respawned worker
                # deserializes from the same artifacts the parent
                # prewarmed
                spec["export_cache"] = export_cache.directory()
            env = dict(os.environ)
            root = _repo_root()
            env["PYTHONPATH"] = (root + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            if not env.get("JAX_PLATFORMS"):
                # tier-1 hermeticity: the worker must land on the
                # SAME backend as the parent even when the env var is
                # unset (the parent may have forced cpu via
                # jax.config, which children cannot inherit)
                try:
                    import jax

                    env["JAX_PLATFORMS"] = jax.default_backend()
                except Exception:
                    pass
            if spec.get("export_cache"):
                env["SINGA_TPU_EXPORT_CACHE"] = spec["export_cache"]
            env["SINGA_TPU_FLEET_SPEC"] = json.dumps(spec)
            self._proc = subprocess.Popen(
                [self._python, "-m", "singa_tpu.fleet_worker"],
                env=env, cwd=root, stdout=subprocess.DEVNULL)
            lsock.settimeout(self.spawn_timeout_s)
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                raise ProcTransportError(
                    f"worker {self.name} did not connect within "
                    f"{self.spawn_timeout_s}s (exit code "
                    f"{self._proc.poll()})")
        finally:
            lsock.close()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(self.spawn_timeout_s)
        reader = FrameReader(max_frame_bytes=self.max_frame_bytes,
                             check_seq=True)
        hello = None
        stashed: List[Tuple[int, int, bytes]] = []
        deadline = time.perf_counter() + self.spawn_timeout_s
        while hello is None:
            if time.perf_counter() > deadline:
                raise ProcTransportError(
                    f"worker {self.name}: no HELLO within "
                    f"{self.spawn_timeout_s}s")
            chunk = conn.recv(65536)
            if not chunk:
                raise ProcTransportError(
                    f"worker {self.name} closed before HELLO (exit "
                    f"code {self._proc.poll()})")
            for ftype, rid, payload in reader.feed(chunk):
                if ftype == HELLO and hello is None:
                    hello = json.loads(payload.decode("utf-8"))
                else:
                    # frames coalesced behind HELLO in one chunk —
                    # the worker's immediate first heartbeat usually
                    # rides here; dropping it would boot every fresh
                    # worker stale
                    stashed.append((ftype, rid, payload))
        if hello.get("token") != token:
            self._proc.kill()
            raise ProcTransportError(
                f"worker {self.name}: HELLO token mismatch")
        self._gen += 1
        gen = self._gen
        self._gens[gen] = _Gen(pid=int(hello.get("pid", -1)))
        with self._wlock:
            self._tx_seq = 0  # fresh connection: both directions at 0
        self._sock = conn
        self.killed = False
        self._draining = False
        conn.settimeout(0.05)
        for ftype, rid, payload in stashed:
            try:
                self._handle_frame(ftype, rid, payload, gen)
            except Exception:
                pass
        self._reader = threading.Thread(
            target=self._read_loop, args=(conn, reader, gen),
            name=f"singa_tpu-proc-{self.name}", daemon=True)
        self._reader.start()
        # The worker sends its first heartbeat right behind HELLO:
        # wait for it so a fresh (or respawned) replica enters the
        # rotation READY instead of spending a stale-ejection round
        # trip on its own boot.
        deadline = time.perf_counter() + min(5.0, self.spawn_timeout_s)
        while self._hb is None and time.perf_counter() < deadline:
            time.sleep(0.002)
        return self

    # -- TCP transport modes (ISSUE 18) -----------------------------------
    def listen_addr(self) -> Tuple[str, int]:
        """The address a worker must `--connect` to: the ChaosProxy's
        front door when net chaos is armed, else the raw listener."""
        if self._proxy is not None:
            return self._proxy.addr
        if self._listen_addr is None:
            raise RuntimeError(f"replica {self.name} is not listening")
        return self._listen_addr

    def net_chaos_snapshot(self) -> Optional[Dict]:
        """The armed `ChaosProxy`'s counter snapshot (frames seen,
        partitions/delays/reorders/dups/drips injected); None when no
        net chaos is armed. Bench reads this to prove the injected
        frame-fault RATE, not just that faults were scheduled. After
        `stop(final=True)` tears the proxy down, the LAST snapshot
        stays readable — evidence survives shutdown."""
        px = self._proxy
        return self._proxy_final if px is None else px.snapshot()

    def _ensure_listener(self) -> None:
        if self._lsock is not None:
            return
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((self._host, self._port))
        lsock.listen(4)
        self._lsock = lsock
        self._listen_addr = lsock.getsockname()[:2]
        if self._net_chaos is not None and self._proxy is None:
            from . import netchaos

            # the proxy IS the network between parent and worker: it
            # persists across worker generations and reconnects
            self._proxy = netchaos.ChaosProxy(
                upstream=self._listen_addr, **self._net_chaos).start()
        t = threading.Thread(target=self._accept_loop, args=(lsock,),
                             name=f"singa_tpu-accept-{self.name}",
                             daemon=True)
        self._accept_thread = t
        t.start()

    def _start_listen(self) -> "ProcReplica":
        if self._sock is not None and not self.killed:
            return self
        self._ensure_listener()
        self._established.clear()
        with self._plock:
            self._reconnecting = False
        self.killed = False
        self._draining = False
        if self._launch == "local" and (
                self._proc is None or self._proc.poll() is not None):
            self._launch_local_worker()
        if not self._established.wait(self.spawn_timeout_s):
            code = None if self._proc is None else self._proc.poll()
            raise ProcTransportError(
                f"worker {self.name}: no authenticated connection on "
                f"{self.listen_addr()} within {self.spawn_timeout_s}s "
                f"(local worker exit code {code})")
        deadline = time.perf_counter() + min(5.0, self.spawn_timeout_s)
        while self._hb is None and time.perf_counter() < deadline:
            time.sleep(0.002)
        return self

    def _start_connect(self) -> "ProcReplica":
        if self._sock is not None and not self.killed:
            return self
        self._established.clear()
        with self._plock:
            self._reconnecting = False
        self.killed = False
        self._draining = False
        try:
            conn = socket.create_connection(
                (self._host, self._port), timeout=self.spawn_timeout_s)
        except OSError as e:
            raise ProcTransportError(
                f"replica {self.name}: cannot dial worker at "
                f"{self._host}:{self._port} ({e})")
        try:
            self._tcp_handshake(conn)
        except Exception:
            try:
                conn.close()
            except OSError:
                pass
            raise
        deadline = time.perf_counter() + min(5.0, self.spawn_timeout_s)
        while self._hb is None and time.perf_counter() < deadline:
            time.sleep(0.002)
        return self

    def _launch_local_worker(self) -> None:
        """The `listen`-mode local launch: the worker gets ONLY the
        CLI a remote host would get (`--connect host:port --token`) —
        no spec in its env, so the WELCOME spec-shipping path is
        exercised on every hermetic run — plus the env hygiene any
        launch recipe needs (PYTHONPATH, backend pin, store dir)."""
        env = dict(os.environ)
        root = _repo_root()
        env["PYTHONPATH"] = (root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        if not env.get("JAX_PLATFORMS"):
            try:
                import jax

                env["JAX_PLATFORMS"] = jax.default_backend()
            except Exception:
                pass
        store = self.spec.get("export_cache") or export_cache.directory()
        if store:
            env["SINGA_TPU_EXPORT_CACHE"] = store
        env.pop("SINGA_TPU_FLEET_SPEC", None)
        host, port = self.listen_addr()
        self._proc = subprocess.Popen(
            [self._python, "-m", "singa_tpu.fleet_worker",
             "--connect", f"{host}:{port}", "--token", self._token,
             "--name", self.name],
            env=env, cwd=root, stdout=subprocess.DEVNULL)

    def _accept_loop(self, lsock: socket.socket) -> None:
        while self._lsock is lsock:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return  # listener closed: replica stopped
            try:
                self._tcp_handshake(conn)
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass

    def _tcp_handshake(self, conn: socket.socket) -> None:
        """Authenticate + fence one inbound/dialed connection. The
        worker speaks first (HELLO {token, fence, need_spec, ...});
        the parent answers WELCOME (adopt or resume) or FENCED
        (refuse) and only then puts the connection in service."""
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(min(10.0, self.spawn_timeout_s))
        reader = FrameReader(max_frame_bytes=self.max_frame_bytes,
                             check_seq=True)
        hello = None
        stashed: List[Tuple[int, int, bytes]] = []
        deadline = time.perf_counter() + self.spawn_timeout_s
        while hello is None:
            if time.perf_counter() > deadline:
                raise ProcTransportError(
                    f"worker {self.name}: no HELLO within "
                    f"{self.spawn_timeout_s}s")
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue
            if not chunk:
                raise ProcTransportError(
                    f"worker {self.name}: connection closed before "
                    "HELLO")
            for ftype, rid, payload in reader.feed(chunk):
                if ftype == HELLO and hello is None:
                    hello = json.loads(payload.decode("utf-8"))
                else:
                    stashed.append((ftype, rid, payload))
        if hello.get("token") != self._token:
            self._refuse(conn, "auth token mismatch")
            raise ProcTransportError(
                f"worker {self.name}: HELLO token mismatch")
        fence = hello.get("fence")
        with self._plock:
            live = self._sock is not None
            resumable = (self._reconnecting and not self.killed
                         and time.perf_counter()
                         < self._reconnect_deadline)
        if fence is None:
            # fresh adoption: a brand-new worker generation
            if live:
                self._refuse(conn, "a live connection already serves "
                                   "the current generation")
                raise ProcTransportError(
                    f"worker {self.name}: second fresh HELLO while a "
                    "connection is live")
            with self._plock:
                self._fence += 1
                self._gen += 1
                gen = self._gen
                self._gens[gen] = _Gen(pid=int(hello.get("pid", -1)))
                self._reconnecting = False
            welcome = {"fence": self._fence, "gen": gen,
                       "reconnect_window_s": self.reconnect_window_s}
            if hello.get("need_spec"):
                spec = _jsonable_spec(self.spec)
                spec.setdefault("name", self.name)
                spec["heartbeat_interval_s"] = self.heartbeat_interval_s
                if trace_mod.enabled():
                    spec.setdefault("trace", {
                        "enabled": True, "ship_capacity": 2048,
                        "ring_capacity":
                            trace_mod.get_config()["ring_capacity"]})
                if "export_cache" not in spec:
                    spec["export_cache"] = export_cache.directory()
                spec.pop("token", None)
                spec.pop("port", None)
                welcome["spec"] = spec
            self._wire_up(conn, reader, gen, welcome, stashed)
            return
        if int(fence) == self._fence and not self.killed:
            # Same-generation reconnect: the fence (token-authed) is
            # the authority, not the parent's view of the old socket —
            # the worker sees an inbound fault FIRST and redials
            # before the parent has noticed anything wrong. The newer
            # connection supersedes the old one: its in-flight
            # requests fail over NOW (PR 11 machinery; replies the
            # worker resends for them dedup by rid, so nothing
            # double-delivers) and the old reader's eventual
            # conn-lost is a recorded no-op.
            with self._plock:
                gen = self._gen
                self._reconnecting = False
                old, self._sock = self._sock, None
                if old is not None:
                    self._superseded.add(old)
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
                self._fail_all_pending(ProcTransportError(
                    f"worker {self.name} (gen {gen}): connection "
                    "superseded by a same-generation reconnect; "
                    "in-flight requests fail over"))
            self.reconnects += 1
            self._wire_up(conn, reader, gen,
                          {"fence": self._fence, "gen": gen,
                           "reconnect_window_s":
                               self.reconnect_window_s,
                           "resumed": True}, stashed)
            return
        # stale (or out-of-window) generation fence: refuse LOUDLY —
        # a replayed/superseded connection must never resurrect a
        # generation the supervisor has moved past
        self.stale_reconnects_refused += 1
        self._refuse(conn, f"stale generation fence {fence} "
                           f"(current {self._fence}, "
                           f"window={'open' if resumable else 'closed'})")
        raise ProcTransportError(
            f"worker {self.name}: stale-generation reconnect refused "
            f"(fence {fence}, current {self._fence})")

    def _refuse(self, conn: socket.socket, reason: str) -> None:
        try:
            send_frame(conn, encode_frame(
                FENCED, 0,
                json.dumps({"reason": reason}).encode("utf-8"),
                seq=0), deadline_s=2.0)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _wire_up(self, conn: socket.socket, reader: FrameReader,
                 gen: int, welcome: Dict, stashed) -> None:
        with self._wlock:
            self._tx_seq = 0  # fresh connection: both directions at 0
        self._sock = conn
        self.killed = False
        conn.settimeout(0.05)
        self._send(WELCOME, 0,
                   json.dumps(welcome).encode("utf-8"))
        for ftype, rid, payload in stashed:
            try:
                self._handle_frame(ftype, rid, payload, gen)
            except Exception:
                pass
        self._reader = threading.Thread(
            target=self._read_loop, args=(conn, reader, gen),
            name=f"singa_tpu-proc-{self.name}", daemon=True)
        self._reader.start()
        self._established.set()

    def _reconnect_active(self) -> bool:
        """True while the bounded reconnect window is open. On expiry
        the generation is DECLARED dead (killed=True) — the supervisor
        restart story takes over — and a lingering local worker is
        reaped so a later respawn cannot race two workers onto one
        device."""
        with self._plock:
            if not self._reconnecting:
                return False
            if time.perf_counter() < self._reconnect_deadline:
                return True
            self._reconnecting = False
        self.killed = True
        self.sigkill()  # no-op for an external worker (no local proc)
        return False

    def _alive(self) -> bool:
        if self.killed:
            return False
        if self._tcp:
            p = self._proc
            if p is not None and p.poll() is not None:
                return False  # local worker observably dead
            if self._sock is not None:
                return True
            return self._reconnect_active()
        return self._proc is not None and self._proc.poll() is None

    def kill(self) -> None:
        """Hard replica death: SIGKILL the worker. In-flight futures
        fail loudly (`ProcTransportError` => router failover), and the
        replica stays dead until `restart()` respawns it."""
        self.killed = True
        self.sigkill()
        self._reap(expected=False)

    def sigkill(self) -> None:
        """The raw chaos primitive (`proc_sigkill`): SIGKILL the
        worker and nothing else — detection (reader EOF, child exit
        code) and recovery (supervisor respawn) must be OBSERVED, not
        arranged."""
        p = self._proc
        if p is not None and p.poll() is None:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass

    def drain_stop(self) -> None:
        """Router drain semantics: the worker stops admitting, fails
        its queued futures (`ServeClosedError` frames => the router
        reroutes them), ships its final counters (BYE), and exits 0.
        TCP listener/proxy stay up — a restart() re-adopts through
        them."""
        self._shutdown(drain=False, timeout=10.0)

    def stop(self, drain: bool = True) -> None:
        self._shutdown(drain=drain, timeout=max(
            10.0, self.spawn_timeout_s / 2), final=True)

    def _shutdown(self, drain: bool, timeout: float,
                  final: bool = False) -> None:
        p = self._proc
        if p is None and self._sock is None and not final:
            return
        self._draining = True
        alive = (p is not None and p.poll() is None) \
            or (p is None and self._sock is not None)
        if alive and self._sock is not None:
            try:
                self._send(CTRL, 0, json.dumps(
                    {"op": "drain", "drain": bool(drain)}
                ).encode("utf-8"))
            except Exception:
                pass
            if p is not None:
                try:
                    p.wait(timeout)
                except subprocess.TimeoutExpired:
                    # a hung dispatch must not block stop forever:
                    # kill, sweep, respawn is the supervisor's problem
                    self.sigkill()
            else:
                # external worker (connect / listen+launch=none): wait
                # for its BYE handshake or EOF, bounded — the parent
                # cannot reap a process it never owned
                dl = time.perf_counter() + timeout
                while time.perf_counter() < dl:
                    g = self._gens.get(self._gen)
                    if self._sock is None or (g is not None and g.clean):
                        break
                    time.sleep(0.02)
        self._reap(expected=True)
        if final:
            self._close_tcp()

    def _close_tcp(self) -> None:
        ls, self._lsock = self._lsock, None
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        self._listen_addr = None
        px, self._proxy = self._proxy, None
        if px is not None:
            # keep the final fault evidence readable after shutdown —
            # the bench reconciles proxy counters at quiescence
            self._proxy_final = px.snapshot()
            px.stop()

    def _reap(self, expected: bool) -> None:
        p, self._proc = self._proc, None
        if p is not None:
            try:
                p.wait(10.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(10.0)
            gen = self._gens.get(self._gen)
            if gen is not None and gen.exit_code is None:
                gen.exit_code = p.returncode
        t, self._reader = self._reader, None
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        if t is not None and t is not threading.current_thread():
            t.join(5.0)
        self._fail_all_pending(ProcTransportError(
            f"worker {self.name} "
            + ("stopped" if expected else "died")
            + f" with the request in flight (gen {self._gen})"))
        if not expected:
            self.killed = True

    def restart(self) -> "ProcReplica":
        """Respawn a fresh worker from the same deterministic spec.
        With the shared store prewarmed the new generation's first
        dispatch of every bucket is a store LOAD — deserialize-only,
        provable from the heartbeat's export counters. TCP modes:
        `listen`+local relaunches the worker through the persistent
        listener (new generation, new fence); `connect` re-dials the
        external worker — which can only be re-adopted FRESH, its old
        fence is dead."""
        if self._proc is not None or self._sock is not None:
            self.sigkill()
            self._reap(expected=True)
        self.restarts += 1
        self._frozen_snap = None
        self._hb = None
        with self._plock:
            self._reconnecting = False
        return self.start()

    # -- request path -----------------------------------------------------
    def _send(self, ftype: int, rid: int, payload: bytes) -> None:
        """Serialize one frame onto the wire UNDER the write lock with
        the partial-write-hardened `send_frame` loop: the socket
        carries a short `settimeout`, and a bare `sendall` under one
        can write a PREFIX of a frame, raise `socket.timeout`, and let
        the next caller interleave its frame mid-frame — permanent
        stream corruption. `send_frame` retries short writes on the
        SAME frame to a deadline; if it still fails, bytes may be out,
        so the connection is poisoned (closed — the reader path then
        fails in-flight requests and, on TCP, opens the reconnect
        window) rather than reused."""
        sock = self._sock
        if sock is None:
            raise ServeClosedError(f"replica {self.name} is dead")
        with self._wlock:
            if self._sock is not sock:
                sock = self._sock  # reconnected under our feet
                if sock is None:
                    raise ServeClosedError(
                        f"replica {self.name} is dead")
            stall, self._stall_s = self._stall_s, 0.0
            if stall > 0:
                time.sleep(stall)  # injected pipe_stall: the write
                # path wedges while holding the pipe, exactly what a
                # full socket buffer looks like from the caller side
            frame = encode_frame(ftype, rid, payload,
                                 seq=self._tx_seq)
            try:
                send_frame(sock, frame,
                           deadline_s=min(self.ipc_deadline_s, 10.0))
            except OSError as e:
                self._poison_conn(sock)
                raise ServeClosedError(
                    f"replica {self.name}: pipe write failed ({e})")
            self._tx_seq += 1

    def _poison_conn(self, sock: socket.socket) -> None:
        """A frame may be HALF-written on this connection: it can
        never carry another frame. Shut it down so the reader thread
        observes the loss and runs the death/reconnect machinery."""
        if self._sock is sock:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def submit(self, *arrays, deadline_ms: Optional[float] = None
               ) -> ServeReply:
        """Submit one request across the boundary. Admission is
        SYNCHRONOUS (REQ -> ACK within the IPC deadline), so every
        submit-time refusal keeps its exact single-engine type and
        the parent's mirrored terminal counters stay one-bucket-per-
        request — the `fleet.reconcile` equations hold unchanged."""
        if not self._alive():
            raise ServeClosedError(f"replica {self.name} is dead")
        if self._tcp and self._sock is None:
            # reconnect window open: there is no pipe to put the
            # request on. Shed LOUDLY (mirrored requests+shed keeps
            # the engine equation exact) with a retry hint sized to
            # the window — the router's shed-aware retry lands it on
            # a healthy replica instead of stranding the caller here.
            note_remote_request()
            note_remote_terminal("shed")
            raise ServeOverloadError(
                f"replica {self.name}: transport reconnecting — "
                "no connection to admit on", retry_after_ms=50.0)
        batch = ServingEngine._as_batch(arrays)
        if not batch:
            raise ValueError("serve request needs at least one input")
        n = int(batch[0].shape[0])
        with self._plock:
            # decode sessions are long-lived streams with their own
            # admission control (the worker's KV-slot pool) — they
            # must not starve the forward lane's in-flight budget
            inflight = sum(1 for e in self._pending.values()
                           if not e.decode)
        if inflight >= self.max_inflight:
            # shed instead of ballooning the pipe: the hint is the
            # worker's own estimate from its last heartbeat
            note_remote_request()
            note_remote_terminal("shed")
            hint = 50.0
            hb = self._hb
            if hb and hb.get("retry_after_ms"):
                hint = float(hb["retry_after_ms"])
            raise ServeOverloadError(
                f"replica {self.name}: {inflight} requests in flight "
                f"at the transport bound ({self.max_inflight}); the "
                "pipe must not balloon — retry after the hinted "
                "backoff", retry_after_ms=hint)
        reply = ServeReply(n)
        with self._plock:
            self._next_id += 1
            rid = self._next_id
            ent = _Pending(reply, self._gen)
            self._pending[rid] = ent
        note_remote_request()
        # Trace context crosses the boundary as an OPTIONAL suffix:
        # with tracing off there is no context and the payload is
        # byte-for-byte the untraced format — zero extra wire bytes.
        trace = None
        if trace_mod.enabled():
            ctx = trace_mod.current_trace()
            if ctx is not None:
                trace = (ctx["trace_id"],
                         trace_mod.current_span_id() or ctx["parent"])
        ent.trace = trace
        payload = encode_req_payload(deadline_ms, batch, trace=trace)
        ent.t_send = time.perf_counter()
        try:
            self._send(REQ, rid, payload)
        except ServeClosedError:
            with self._plock:
                popped = self._pending.pop(rid, None)
                claim = popped is not None and popped.take_claim()
            if claim:
                note_remote_terminal("failed")
            err = ServeClosedError(
                f"replica {self.name} died before the request was "
                "admitted")
            err.counted = True
            raise err
        if not ent.ack_ev.wait(self.ipc_deadline_s):
            # no admission verdict in time: fail THIS caller loudly
            # and keep the ledger exact — if the worker later admits
            # it, the late ACK/REP land on the already-failed future
            # and are dropped (first write wins), counted as frames.
            with self._plock:
                claim = ent.take_claim()
            self.ipc_timeouts += 1
            reply._fail(ProcTransportError(
                f"replica {self.name}: no admission ACK within "
                f"{self.ipc_deadline_s * 1e3:.0f} ms (worker hung "
                "or pipe stalled)"))
            if claim:
                # failed (never admitted): the request never entered
                # `sent`, so it must not enter `transport_failed` —
                # the parent-terminals equation covers ADMITTED
                # requests only; this one is a submit-time refusal
                # the router books as `refused`.
                note_remote_terminal("failed")
            err = ServeClosedError(
                f"replica {self.name}: admission timed out")
            err.counted = True
            raise err
        if ent.ack_err is not None:
            raise ent.ack_err
        # admitted: arm the in-flight IPC deadline (transport bound on
        # top of the caller's own deadline — the worker expires THAT)
        user_s = 0.0 if deadline_ms is None else float(deadline_ms) / 1e3
        ent.ipc_abs = time.perf_counter() + self.ipc_deadline_s + user_s
        self.sent += 1
        return reply

    def submit_decode(self, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      seed: int = 0,
                      deadline_ms: Optional[float] = None) -> ServeReply:
        """Submit one generative session across the boundary
        (`ServingEngine.submit_decode`, DECODE frame). Admission is
        synchronous like `submit` — a refusal keeps its exact engine
        type (`ServeOverloadError.retry_after_ms` is the worker's own
        slot-pool hint) — and the returned reply's `tokens()` stream
        is fed by TOK frames as the worker's fused steps land, with
        the final REP delivering the full `[1, P + n]` array. A drain
        mid-stream fails the reply with `ServeMigratedError` carrying
        the checkpoint (MIGRATE frame) for re-placement."""
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None, :]
        if prompt.ndim != 2 or prompt.shape[0] != 1 \
                or prompt.shape[1] < 1:
            raise ValueError(
                f"decode prompt must be [P] or [1, P] token ids, got "
                f"shape {prompt.shape}")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        trace = None
        if trace_mod.enabled():
            ctx = trace_mod.current_trace()
            if ctx is not None:
                trace = (ctx["trace_id"],
                         trace_mod.current_span_id() or ctx["parent"])
        payload = encode_decode_payload(
            prompt, max_new_tokens, temperature, top_k, seed,
            deadline_ms, trace=trace)
        return self._decode_roundtrip(DECODE, payload, deadline_ms,
                                      trace)

    def resume_decode(self, ckpt: Dict) -> ServeReply:
        """Admit a migrated session's checkpoint on THIS replica
        (RESUME frame -> `ServingEngine.resume_decode`): the worker
        re-streams the ledger prefix through TOK frames first, then
        the live continuation — one seamless stream for a consumer
        that dedupes by count."""
        trace = None
        if trace_mod.enabled():
            ctx = trace_mod.current_trace()
            if ctx is not None:
                trace = (ctx["trace_id"],
                         trace_mod.current_span_id() or ctx["parent"])
        dl = ckpt.get("deadline_ms_left")
        payload = encode_resume_payload(ckpt, trace=trace)
        return self._decode_roundtrip(RESUME, payload,
                                      None if dl is None else float(
                                          np.asarray(dl)), trace)

    def _decode_roundtrip(self, ftype: int, payload: bytes,
                          deadline_ms: Optional[float],
                          trace) -> ServeReply:
        """The shared DECODE/RESUME admission dance — `submit`'s
        REQ -> ACK protocol with the terminal mirrors routed into the
        decode-session books (`note_remote_decode_*`) instead of the
        forward ones. Sessions do NOT count toward `max_inflight`
        (they are long-lived streams; the worker's KV-slot pool is
        their admission control) and carry no transport sweep deadline
        unless the session itself has one."""
        if not self._alive():
            raise ServeClosedError(f"replica {self.name} is dead")
        if self._tcp and self._sock is None:
            # reconnect window open: shed the session loudly, exactly
            # like the worker's own slot-pool refusal (sessions+shed
            # keeps the decode equation exact)
            note_remote_decode_session(resumed=(ftype == RESUME))
            note_remote_decode_terminal("shed")
            raise ServeOverloadError(
                f"replica {self.name}: transport reconnecting — "
                "no connection to admit the session on",
                retry_after_ms=50.0)
        reply = ServeReply(1)
        with self._plock:
            self._next_id += 1
            rid = self._next_id
            ent = _Pending(reply, self._gen)
            ent.decode = True
            self._pending[rid] = ent
        note_remote_decode_session(resumed=(ftype == RESUME))
        ent.trace = trace
        ent.t_send = time.perf_counter()
        try:
            self._send(ftype, rid, payload)
        except ServeClosedError:
            with self._plock:
                popped = self._pending.pop(rid, None)
                claim = popped is not None and popped.take_claim()
            if claim:
                note_remote_decode_terminal("failed")
            err = ServeClosedError(
                f"replica {self.name} died before the decode session "
                "was admitted")
            err.counted = True
            raise err
        if not ent.ack_ev.wait(self.ipc_deadline_s):
            with self._plock:
                claim = ent.take_claim()
            self.ipc_timeouts += 1
            reply._fail(ProcTransportError(
                f"replica {self.name}: no decode admission ACK within "
                f"{self.ipc_deadline_s * 1e3:.0f} ms (worker hung or "
                "pipe stalled)"))
            if claim:
                note_remote_decode_terminal("failed")
            err = ServeClosedError(
                f"replica {self.name}: decode admission timed out")
            err.counted = True
            raise err
        if ent.ack_err is not None:
            raise ent.ack_err
        if deadline_ms is not None:
            # transport bound past the session's own deadline — the
            # worker expires THAT; a deadline-free session is bounded
            # by its token budget, not by the IPC sweep
            ent.ipc_abs = (time.perf_counter() + self.ipc_deadline_s
                           + float(deadline_ms) / 1e3)
        self.decode_sent += 1
        return reply

    def warmup(self, *arrays) -> int:
        batch = ServingEngine._as_batch(arrays)
        res = self._ctrl_sync(WARM, encode_tree(list(batch)),
                              timeout=self.spawn_timeout_s)
        return int(res.get("warmed", 0))

    def warm_decode(self, prompt_lens=(), max_new_tokens=None,
                    samplers=()) -> int:
        """Worker-side `ServingEngine.warm_decode` over the wire: with
        the shared store prewarmed this is deserialize-only — the
        respawn-readiness probe the decode tier's restart story pins
        (store hits >= 1, traces == 0, from `counters()`)."""
        res = self._ctrl_sync(CTRL, json.dumps(
            {"op": "warm_decode",
             "prompt_lens": [int(p) for p in prompt_lens],
             "max_new_tokens": max_new_tokens,
             "samplers": [[float(t), int(k)] for t, k in samplers]}
            ).encode("utf-8"),
            timeout=self.spawn_timeout_s)
        return int(res.get("warmed", 0))

    def counters(self, timeout: float = 5.0) -> Dict:
        """Live reconciliation probe: the worker's CURRENT terminal +
        export counters (the same payload the BYE handshake ships)."""
        return self._ctrl_sync(
            CTRL, json.dumps({"op": "counters"}).encode("utf-8"),
            timeout=timeout)

    def _ctrl_sync(self, ftype: int, payload: bytes,
                   timeout: float) -> Dict:
        if not self._alive():
            raise ServeClosedError(f"replica {self.name} is dead")
        ev = threading.Event()
        box: Dict = {}
        with self._plock:
            self._next_id += 1
            rid = self._next_id
            self._ctrl_pending[rid] = {"ev": ev, "box": box}
        try:
            self._send(ftype, rid, payload)
            if not ev.wait(timeout):
                raise ProcTransportError(
                    f"replica {self.name}: control round-trip timed "
                    f"out after {timeout}s")
        finally:
            with self._plock:
                self._ctrl_pending.pop(rid, None)
        return box.get("result", {})

    # -- health/load signals ----------------------------------------------
    def health(self) -> Dict:
        """The last HEARTBEAT's health snapshot, with the worker's own
        wall-clock stamp — a dead or wedged worker stops refreshing
        it, the snapshot ages, and the router's stale-snapshot
        ejection fires (missed heartbeat => stale => fail closed,
        the PR 11 path verbatim)."""
        if (self._frozen_snap is not None
                and time.perf_counter() < self._frozen_until):
            return dict(self._frozen_snap)
        if not self._alive():
            g = self._gens.get(self._gen)
            code = None if g is None else g.exit_code
            return {"state": "unhealthy",
                    "reasons": [f"worker {self.name} dead (exit code "
                                f"{code})"],
                    "time": round(time.time(), 3), "name": self.name}
        if self._tcp and self._sock is None:
            # reconnect window open: fail closed NOW (the router
            # ejects and routes around) — an unstamped snapshot also
            # reads as stale, so both freshness paths agree
            return {"state": "unhealthy",
                    "reasons": ["connection lost; reconnect window "
                                "open"],
                    "name": self.name}
        hb = self._hb
        if hb is None:
            # spawned but no heartbeat yet: an unstamped snapshot
            # reads as stale — fail closed until the worker proves
            # itself
            return {"state": "unhealthy",
                    "reasons": ["no heartbeat received yet"],
                    "name": self.name}
        snap = dict(hb.get("health") or {})
        snap.setdefault("name", self.name)
        return snap

    def depth(self) -> int:
        with self._plock:
            return len(self._pending)

    def slo_probe(self) -> Dict:
        """Anomaly-detector inputs for the router's SLO tick (ISSUE
        20): heartbeat age and the current generation's clock offset
        next to the transport's OWN uncertainty estimate — the
        detector thresholds on what the estimator admits it doesn't
        know, not on a magic constant."""
        out: Dict = {"hb_gap_s": None, "clock_offset_us": None,
                     "clock_uncertainty_us": None}
        if self._hb_rx:  # 0.0 == no heartbeat yet: nothing to gap
            out["hb_gap_s"] = time.perf_counter() - self._hb_rx
        with self._plock:
            g = self._gens.get(self._gen)
            if g is not None and g.clock_offset_us is not None:
                out["clock_offset_us"] = g.clock_offset_us
                out["clock_uncertainty_us"] = g.clock.uncertainty_us()
        return out

    def device_token(self):
        """Two workers pinned to one device id would contend for the
        same chip under load — surface it at fleet construction (the
        router's shared-device warning), not as mystery latency."""
        idx = (self.spec.get("factory_kwargs") or {}).get(
            "device_index")
        return None if idx is None else ("proc-device", int(idx))

    def transport_snapshot(self) -> Dict:
        """Lifetime transport counters + per-generation ledger (the
        `fleet.reconcile_transport` input)."""
        with self._plock:
            gens = {
                g: {"admitted": gen.admitted, "frames": gen.frames,
                    "swept": gen.swept, "migrated": gen.migrated,
                    "ack_errs": gen.ack_errs,
                    "clean": gen.clean, "exit_code": gen.exit_code,
                    "handshake": gen.handshake,
                    "pid": gen.pid,
                    "clock_offset_us": gen.clock_offset_us,
                    "clock_rtt_s": gen.clock_rtt_s,
                    "clock_uncertainty_us": gen.clock.uncertainty_us()}
                for g, gen in self._gens.items()}
            return {
                "sent": self.sent,
                "delivered": self.delivered,
                "err_replies": self.err_replies,
                "transport_failed": self.transport_failed,
                "ipc_timeouts": self.ipc_timeouts,
                "torn_frames_detected": self.torn_frames_detected,
                "replay_frames_detected": self.replay_frames_detected,
                "gap_frames_detected": self.gap_frames_detected,
                "pending": len(self._pending),
                "heartbeats": self.hb_received,
                "mode": self._mode,
                "fence": self._fence,
                "reconnects": self.reconnects,
                "reconnect_windows": self.reconnect_windows,
                "stale_reconnects_refused":
                    self.stale_reconnects_refused,
                "spans_received": self.spans_received,
                "spans_dropped": self.spans_dropped,
                "decode": {
                    "sent": self.decode_sent,
                    "delivered": self.decode_delivered,
                    "err_replies": self.decode_err_replies,
                    "transport_failed": self.decode_transport_failed,
                    "migrated_out": self.migrated_out,
                    "tokens": self.decode_tokens,
                },
                "generations": gens,
            }

    # -- chaos hooks -------------------------------------------------------
    def hang_once(self, hang_s: float) -> None:
        """`replica_hang`/`proc_hang`: the worker's next dispatch
        attempt sleeps `hang_s` (one-shot, armed over the wire)."""
        try:
            self._send(CTRL, 0, json.dumps(
                {"op": "hang_once", "s": float(hang_s)}
            ).encode("utf-8"))
        except ServeClosedError:
            pass

    def freeze_health(self, for_s: float) -> None:
        """`stale_health`: freeze the health surface on the current
        snapshot — its timestamp stops advancing, so the router must
        eject once `health_max_age_s` passes."""
        self._frozen_snap = self.health()
        self._frozen_until = time.perf_counter() + float(for_s)

    def stall_pipe(self, stall_s: float) -> None:
        """`pipe_stall`: the parent's NEXT frame write sleeps
        `stall_s` while holding the pipe — admission ACKs back up
        behind it and the IPC deadline machinery must absorb it."""
        self._stall_s = float(stall_s)

    def tear_next_frame(self) -> None:
        """`torn_frame`: the worker corrupts its next reply frame.
        The parent's CRC check must refuse it, fail in-flight futures
        loudly, and kill/respawn the worker — a truncated reply can
        never be delivered as data."""
        try:
            self._send(CTRL, 0, json.dumps(
                {"op": "torn_frame"}).encode("utf-8"))
        except ServeClosedError:
            pass

    def net_fault(self, kind: str, **kw) -> None:
        """Route a `net_*` chaos kind into the replica's armed
        `ChaosProxy` (no-op without one — the router's chaos layer
        probes via getattr, same as the other proc-only kinds):
        partition/half_open are timed both/one-direction stalls, the
        rest arm the proxy's next-frame one-shots."""
        px = self._proxy
        if px is None:
            return
        if kind == "net_partition":
            px.partition(float(kw.get("t_s", 0.4)))
        elif kind == "net_half_open":
            px.half_open(float(kw.get("t_s", 0.3)),
                         direction=kw.get("direction", "u2c"))
        elif kind == "net_delay":
            px.delay_next(float(kw.get("ms", 5.0)))
        elif kind == "net_reorder":
            px.reorder_next()
        elif kind == "net_dup":
            px.duplicate_next()
        elif kind == "net_drip":
            px.drip_next()

    # -- reader thread -----------------------------------------------------
    def _read_loop(self, sock: socket.socket, reader: FrameReader,
                   gen: int) -> None:
        while True:
            if self._sock is not sock:
                return  # superseded by a restart
            try:
                chunk = sock.recv(1 << 16)
            except socket.timeout:
                self._sweep_deadlines()
                p = self._proc
                dead = (p.poll() is not None if p is not None
                        else not self._tcp)
                if dead and reader.pending_bytes() == 0:
                    self._on_dead(gen, sock)
                    return
                continue
            except OSError:
                self._on_conn_lost(gen, sock)
                return
            if not chunk:
                self._on_conn_lost(gen, sock)
                return
            try:
                frames = reader.feed(chunk)
            except FrameCorruptError as e:
                self._on_corrupt(gen, sock, e)
                return
            for ftype, rid, payload in frames:
                try:
                    self._handle_frame(ftype, rid, payload, gen)
                except FrameCorruptError as e:
                    self._on_corrupt(gen, sock, e)
                    return
                except Exception:
                    pass  # one bad record must not kill the reader
            self._sweep_deadlines()

    def _handle_frame(self, ftype: int, rid: int, payload: bytes,
                      gen: int) -> None:
        g = self._gens[gen]
        if ftype == ACK:
            t_recv = time.perf_counter()
            with self._plock:
                ent = self._pending.get(rid)
                if ent is None:
                    return
                ent.acked = True
                g.admitted += 1
            if len(payload) == 8 and ent.t_send is not None:
                # traced ACK: the worker stamped its perf_counter —
                # midpoint-minus-stamp is the clock offset, and the
                # smallest-RTT handshake gives the tightest estimate
                (t_w,) = struct.unpack(">d", payload)
                g.clock.add(ent.t_send, t_recv, t_w)
                g.clock_rtt_s = g.clock.rtt_s()
                g.clock_offset_us = g.clock.offset_us()
                if ent.trace is not None:
                    # the IPC transit leg of this request's timeline
                    trace_mod.record_span(
                        "ipc", ent.t_send, t_recv, trace=ent.trace,
                        replica=self.name)
                    slo_mod.observe("ipc", t_recv - ent.t_send)
            ent.ack_ev.set()
        elif ftype == REP:
            with self._plock:
                ent = self._pending.pop(rid, None)
                if ent is not None:
                    g.frames += 1
            if ent is None:
                return
            try:
                flags = payload[0]
                late = bool(flags & 1)
                value, off = decode_tree_prefix(payload, 1)
                if flags & 2:
                    # piggybacked worker spans (bounded per frame)
                    (sn,) = struct.unpack_from(">I", payload, off)
                    off += 4
                    self._note_shipped(gen, json.loads(
                        payload[off:off + sn].decode("utf-8")))
                    off += sn
                if off != len(payload):
                    raise FrameCorruptError(
                        f"{len(payload) - off} trailing bytes after "
                        "the reply tree: codec desync")
            except Exception as e:
                # CRC passed but the payload does not decode (codec
                # desync / version skew): the entry is already popped,
                # so fail ITS future here — a stranded caller would
                # hang past every failover — then treat the stream as
                # corrupt like any other framing damage.
                if ent.reply._fail(ProcTransportError(
                        f"replica {self.name}: reply frame {rid} "
                        f"failed to decode ({e!r})")):
                    self.transport_failed += 1
                    note_remote_terminal("failed")
                raise FrameCorruptError(
                    f"undecodable REP payload for {rid}: {e!r}")
            if late:
                ent.reply.deadline_exceeded = True
            if ent.reply._deliver(value):
                if ent.decode:
                    self.decode_delivered += 1
                    note_remote_decode_terminal("completed")
                else:
                    self.delivered += 1
                    note_remote_terminal("replies", late=late)
        elif ftype == TOK:
            with self._plock:
                ent = self._pending.get(rid)
            if ent is None or not ent.decode:
                return  # late token for a swept/unknown session:
                # dropped — never appended to a terminal stream
            toks = np.frombuffer(payload, ">i4")
            for t in toks:
                ent.reply._push_token(int(t))
            self.decode_tokens += len(toks)
            note_remote_decode_tokens(len(toks))
        elif ftype == MIGRATE:
            ckpt = decode_tree(payload)
            with self._plock:
                ent = self._pending.pop(rid, None)
                if ent is not None:
                    g.migrated += 1
            if ent is None:
                return
            # the session LEFT this replica's books without a terminal
            # (the worker already decremented its own `sessions`):
            # mirror the net-out, then hand the checkpoint to whoever
            # holds the reply — the fleet's stream proxy re-places it.
            # Mirror ONLY on the first-write win: a sweep-failed
            # session already booked its terminal, and netting it out
            # here too would break the parent's 4-equation books.
            if ent.reply._fail(ServeMigratedError(
                    f"replica {self.name}: decode session migrated "
                    "off the draining worker "
                    f"({len(np.asarray(ckpt.get('toks', ())).ravel())}"
                    " tokens in the ledger)", ckpt=ckpt)):
                self.migrated_out += 1
                note_remote_decode_export()
        elif ftype == ERR:
            d = json.loads(payload.decode("utf-8"))
            err = decode_error(d)
            with self._plock:
                ent = self._pending.pop(rid, None)
                if ent is None:
                    return
                if not ent.acked:
                    # admission refusal: record the verdict and take
                    # the one-terminal claim under the SAME lock the
                    # submit()-timeout path uses — both firing would
                    # mirror two terminals for one request
                    g.ack_errs += 1
                    ent.ack_err = err
                    claim = ent.take_claim()
            if not ent.acked:
                if claim:
                    kind = d.get("kind", "dispatch")
                    if ent.decode:
                        note_remote_decode_terminal(
                            _DECODE_ERR_TERMINAL.get(kind, "failed"))
                    else:
                        note_remote_terminal({
                            "overload": "shed",
                            "queue_full": "dropped",
                            "overflow": "overflowed",
                        }.get(kind, "failed"))
                if isinstance(err, ServeClosedError):
                    # the parent mirrored requests+<terminal> for
                    # this refusal: the router must count it
                    # `refused` so the routing equation stays exact
                    err.counted = True
                ent.ack_ev.set()
                return
            with self._plock:
                g.frames += 1
            if ent.reply._fail(err):
                if ent.decode:
                    self.decode_err_replies += 1
                    note_remote_decode_terminal(
                        _DECODE_ERR_TERMINAL.get(
                            d.get("kind", "dispatch"), "failed"))
                else:
                    self.err_replies += 1
                    note_remote_terminal(_ERR_TERMINAL.get(
                        d.get("kind", "dispatch"), "failed"))
        elif ftype == HB:
            t_rx = time.perf_counter()
            hb = json.loads(payload.decode("utf-8"))
            spans = hb.pop("spans", None)
            if spans:
                self._note_shipped(gen, spans)
            s_payload = hb.pop("slo", None)
            if s_payload is not None:
                # ISSUE 20: cumulative sketch payload, last-writer-
                # wins keyed by (replica, generation) — a stale
                # generation's heartbeat can never clobber the
                # respawn's fresh sketches
                slo_mod.ingest_wire(self.name, s_payload, gen=gen)
            clock = hb.get("clock")
            if clock and g.clock_wall_us is None:
                # wall-clock fallback offset (same host, so the wall
                # clocks agree): parent-mono-at-send ~= t_rx adjusted
                # by the wall delta; only the ACK handshake refines it
                g.clock_wall_us = ((clock["wall"] - time.time() + t_rx)
                                   - clock["mono"]) * 1e6
            self._hb = hb
            self._hb_rx = t_rx
            self.hb_received += 1
        elif ftype == CTRL_OK:
            with self._plock:
                waiter = self._ctrl_pending.get(rid)
            if waiter is not None:
                waiter["box"]["result"] = json.loads(
                    payload.decode("utf-8"))
                waiter["ev"].set()
        elif ftype == BYE:
            bye = json.loads(payload.decode("utf-8"))
            spans = bye.pop("spans", None)
            if spans:
                self._note_shipped(gen, spans)
            s_payload = bye.pop("slo", None)
            if s_payload is not None:
                # final cumulative state at clean shutdown — nothing
                # sampled after the last heartbeat is lost
                slo_mod.ingest_wire(self.name, s_payload, gen=gen)
            g.handshake = bye
            g.clean = True

    def _note_shipped(self, gen: int, spans) -> None:
        """Buffer shipped worker spans (bounded — overflow drops the
        OLDEST, counted `spans_dropped`, never an unbounded list)."""
        with self._plock:
            for rec in spans:
                if not isinstance(rec, dict) or "name" not in rec:
                    continue
                if len(self._shipped) >= _MAX_SHIPPED:
                    self._shipped.popleft()
                    self.spans_dropped += 1
                self._shipped.append((gen, rec))
                self.spans_received += 1

    def trace_source(self):
        """Span sources for `trace.merge_chrome_traces`: one per
        worker GENERATION that shipped spans, each carrying that
        generation's pid and estimated clock offset — a respawned
        worker is a new process with a new `perf_counter` origin, so
        its spans need their own shift."""
        with self._plock:
            by_gen: Dict[int, List[Dict]] = {}
            for gnum, rec in self._shipped:
                by_gen.setdefault(gnum, []).append(rec)
        out = []
        for gnum, recs in sorted(by_gen.items()):
            g = self._gens.get(gnum)
            out.append({
                "records": recs,
                "pid": None if g is None else g.pid,
                "offset_us": 0.0 if g is None else g.offset_us(),
                "replica": self.name,
                "gen": gnum,
            })
        return out

    def _sweep_deadlines(self) -> None:
        now = time.perf_counter()
        victims: List[_Pending] = []
        with self._plock:
            for ent in self._pending.values():
                if (ent.acked and not ent.sweep_failed
                        and ent.ipc_abs is not None
                        and now >= ent.ipc_abs):
                    ent.sweep_failed = True
                    victims.append(ent)
        for ent in victims:
            self.ipc_timeouts += 1
            if ent.reply._fail(ProcTransportError(
                    f"replica {self.name}: no reply within the IPC "
                    f"deadline ({self.ipc_deadline_s * 1e3:.0f} ms "
                    "past the request deadline) — worker hung or "
                    "pipe stalled")):
                if ent.decode:
                    self.decode_transport_failed += 1
                    note_remote_decode_terminal("failed")
                else:
                    self.transport_failed += 1
                    note_remote_terminal("failed")
            # the entry STAYS pending: if the worker is merely slow
            # its frame still arrives (dropped, but counted), and if
            # the worker dies the death sweep moves it to `swept` —
            # either way the generation ledger closes exactly.

    def _fail_all_pending(self, err: BaseException) -> None:
        with self._plock:
            victims = list(self._pending.items())
            self._pending.clear()
            ctrl = list(self._ctrl_pending.values())
            self._ctrl_pending.clear()
        for rid, ent in victims:
            with self._plock:
                g = self._gens.get(ent.gen)
                if g is not None and ent.acked:
                    g.swept += 1
                claim = (not ent.acked) and ent.take_claim()
            won = ent.reply._fail(err)
            if not ent.acked:
                # submit() is still waiting on the ACK: wake it with
                # the terminal error so the caller is never stranded.
                # counted=True: the failed bucket below keeps the
                # engine equation exact, so the router must book the
                # refusal too.
                ent.ack_err = ServeClosedError(str(err))
                ent.ack_err.counted = True
                ent.ack_ev.set()
                if claim:
                    # never admitted => never in `sent`: mirror the
                    # terminal but keep it out of transport_failed
                    # (the parent-terminals equation is over admitted
                    # requests only)
                    if ent.decode:
                        note_remote_decode_terminal("failed")
                    else:
                        note_remote_terminal("failed")
                continue
            if won:
                if ent.decode:
                    # a SIGKILLed worker's live sessions fail LOUDLY
                    # here; the fleet's stream proxy re-prefills from
                    # its delivered-token ledger (replay — migration
                    # is only the fast path)
                    self.decode_transport_failed += 1
                    note_remote_decode_terminal("failed")
                else:
                    self.transport_failed += 1
                    note_remote_terminal("failed")
        for waiter in ctrl:
            waiter["ev"].set()

    def _on_conn_lost(self, gen: int, sock: socket.socket) -> None:
        """Socket EOF/error. Spawn mode: the connection IS the process
        — child death. TCP modes: the connection is only the NETWORK;
        unless the (local) worker is observably dead or the stop path
        asked for this, the generation gets its bounded reconnect
        window: in-flight requests fail over NOW (PR 11 machinery —
        never hang), health reads unhealthy so the router ejects, and
        a reconnect HELLO carrying the current fence resumes the same
        generation. Window expiry (checked by the health/liveness
        probes) declares the generation dead."""
        with self._plock:
            if sock in self._superseded:
                # a same-fence reconnect already replaced this
                # connection — its loss is old news, not a new window
                self._superseded.discard(sock)
                return
        if not self._tcp:
            self._on_dead(gen, sock)
            return
        g = self._gens.get(gen)
        p = self._proc
        if (self._draining or self.killed
                or (g is not None and g.clean)
                or (p is not None and p.poll() is not None)):
            self._on_dead(gen, sock)
            return
        fresh = False
        with self._plock:
            if self._sock is sock:
                self._sock = None
            if not self._reconnecting:
                self._reconnecting = True
                fresh = True
            self._reconnect_deadline = (time.perf_counter()
                                        + self.reconnect_window_s)
        try:
            sock.close()
        except OSError:
            pass
        if fresh:
            self.reconnect_windows += 1
        self._fail_all_pending(ProcTransportError(
            f"worker {self.name} (gen {gen}) connection lost; "
            "in-flight requests fail over while the worker gets a "
            f"{self.reconnect_window_s:g}s reconnect window"))
        if self._mode == "connect":
            t = threading.Thread(target=self._redial_loop,
                                 name=f"singa_tpu-redial-{self.name}",
                                 daemon=True)
            t.start()

    def _redial_loop(self) -> None:
        """`connect` mode owns re-establishment from the parent side:
        seeded-backoff redials of the worker's listen address until
        the handshake resumes the generation or the window expires."""
        from . import resilience

        attempt = 0
        while True:
            with self._plock:
                if (not self._reconnecting or self._sock is not None
                        or self.killed):
                    return
                deadline = self._reconnect_deadline
            attempt += 1
            delay = resilience.backoff_delay_s(
                attempt, 0.05, seed=hash(self.name) & 0x7FFFFFFF,
                salt="redial")
            if time.perf_counter() + delay >= deadline:
                time.sleep(max(0.0, deadline - time.perf_counter()))
                self._reconnect_active()  # flips killed on expiry
                return
            time.sleep(delay)
            try:
                conn = socket.create_connection(
                    (self._host, self._port), timeout=5.0)
            except OSError:
                continue
            try:
                self._tcp_handshake(conn)
                return
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass

    def _on_dead(self, gen: int, sock: socket.socket) -> None:
        p = self._proc
        code = None
        if p is not None:
            try:
                # EOF usually beats the kernel's exit bookkeeping by
                # a hair: wait for the real exit code — the child
                # exit code IS the crash-detection evidence
                code = p.wait(5.0)
            except subprocess.TimeoutExpired:
                code = p.poll()
        g = self._gens.get(gen)
        if g is not None and g.exit_code is None:
            g.exit_code = code
        if self._sock is sock:
            self._sock = None
            try:
                sock.close()
            except OSError:
                pass
        if not self._draining and not (g is not None and g.clean):
            self.killed = True
        self._fail_all_pending(ProcTransportError(
            f"worker {self.name} (gen {gen}) died with the request "
            f"in flight (exit code {code})"))

    def _on_corrupt(self, gen: int, sock: socket.socket,
                    e: FrameCorruptError) -> None:
        """Fail closed on stream corruption: every in-flight future
        fails LOUDLY — a corrupt stream cannot be resynced by
        guessing. Spawn mode kills the worker for respawn (the
        connection is the process). TCP modes tear down only the
        CONNECTION: corruption there indicts the network (duplicated,
        reordered, torn frames), not the process, so the worker gets
        its reconnect window and a FRESH stream (sequence numbers
        restart) — replay/gap damage is counted per taxonomy either
        way and never delivered as data."""
        self.torn_frames_detected += 1
        if isinstance(e, FrameReplayError):
            self.replay_frames_detected += 1
        elif isinstance(e, FrameGapError):
            self.gap_frames_detected += 1
        import sys as _sys

        print(f"singa_tpu: replica {self.name} frame stream corrupt "
              f"({e}); failing in-flight requests and "
              + ("dropping the connection for reconnect"
                 if self._tcp else "killing the worker for respawn"),
              file=_sys.stderr)
        if self._tcp and not self._draining:
            self._on_conn_lost(gen, sock)
            return
        self.killed = True
        self.sigkill()
        self._on_dead(gen, sock)
