"""SONNX: ONNX import/export over the autograd op registry.

Reference parity: `python/singa/sonnx.py` (SURVEY.md §2.2 P7) —
`SingaFrontend.to_onnx` (walk the creator graph, rename ops),
`SingaBackend.prepare(model, device)` → `SingaRep.run(inputs)`, and
`SONNXModel` (a `Model` subclass wrapping an imported graph for
fine-tuning — the BERT config's entry point, SURVEY.md §3.4).

TPU-native difference: the environment has no `onnx` pip package, so
serialization uses `singa_tpu.proto.onnx_ir_pb2`, a wire-compatible
subset of the public ONNX schema compiled with protoc — files written
here load in stock onnx tooling and vice versa. Execution of an
imported graph dispatches to the same autograd ops as native models,
so imported graphs are differentiable, jit-able (`Model.compile`) and
mesh-shardable like everything else.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from . import autograd, model as model_mod, tensor as tensor_mod
from .device import get_default_device
from .ops import native
from .proto import onnx_ir_pb2 as P
from .tensor import Tensor

OPSET_VERSION = 13
IR_VERSION = 8

# ---------------------------------------------------------------------------
# numpy <-> TensorProto
# ---------------------------------------------------------------------------
_NP2ONNX = {
    np.dtype(np.float32): P.TensorProto.FLOAT,
    np.dtype(np.uint8): P.TensorProto.UINT8,
    np.dtype(np.int8): P.TensorProto.INT8,
    np.dtype(np.uint16): P.TensorProto.UINT16,
    np.dtype(np.int16): P.TensorProto.INT16,
    np.dtype(np.int32): P.TensorProto.INT32,
    np.dtype(np.int64): P.TensorProto.INT64,
    np.dtype(np.bool_): P.TensorProto.BOOL,
    np.dtype(np.float16): P.TensorProto.FLOAT16,
    np.dtype(np.float64): P.TensorProto.DOUBLE,
    np.dtype(np.uint32): P.TensorProto.UINT32,
    np.dtype(np.uint64): P.TensorProto.UINT64,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def to_tensor_proto(name: str, arr) -> P.TensorProto:
    arr = np.asarray(arr)
    if arr.dtype == jnp.bfloat16 or str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    tp = P.TensorProto()
    tp.name = name
    tp.dims.extend(arr.shape)
    tp.data_type = _NP2ONNX[arr.dtype]
    tp.raw_data = np.ascontiguousarray(arr).tobytes()
    return tp


def to_numpy(tp: P.TensorProto) -> np.ndarray:
    dtype = _ONNX2NP[tp.data_type]
    shape = tuple(tp.dims)
    if tp.raw_data:
        return np.frombuffer(tp.raw_data, dtype=dtype).reshape(shape).copy()
    if tp.int32_data and tp.data_type == P.TensorProto.FLOAT16:
        # The ONNX spec stores fp16 as raw bit patterns in int32_data;
        # reinterpret, don't numerically cast.
        return (np.asarray(tp.int32_data, np.int32).astype(np.uint16)
                .view(np.float16).reshape(shape))
    if tp.float_data:
        return np.asarray(tp.float_data, np.float32).astype(dtype).reshape(shape)
    if tp.int64_data:
        return np.asarray(tp.int64_data, np.int64).astype(dtype).reshape(shape)
    if tp.int32_data:
        return np.asarray(tp.int32_data, np.int32).astype(dtype).reshape(shape)
    if tp.double_data:
        return np.asarray(tp.double_data, np.float64).astype(dtype).reshape(shape)
    return np.zeros(shape, dtype)


def _elem_type(dtype) -> int:
    """ONNX elem_type for a value-info dtype; bf16 maps to BFLOAT16=16
    (it is not in _NP2ONNX since numpy has no native bfloat16)."""
    if str(dtype) == "bfloat16":
        return P.TensorProto.BFLOAT16
    return _NP2ONNX[np.dtype(dtype)]


def _attr(node: P.NodeProto, name: str, default=None):
    for a in node.attribute:
        if a.name != name:
            continue
        t = a.type
        if t == P.AttributeProto.FLOAT:
            return a.f
        if t == P.AttributeProto.INT:
            return a.i
        if t == P.AttributeProto.STRING:
            return a.s.decode()
        if t == P.AttributeProto.TENSOR:
            return to_numpy(a.t)
        if t == P.AttributeProto.FLOATS:
            return list(a.floats)
        if t == P.AttributeProto.INTS:
            return list(a.ints)
        if t == P.AttributeProto.STRINGS:
            return [s.decode() for s in a.strings]
    return default


def _make_attr(name: str, value) -> P.AttributeProto:
    a = P.AttributeProto()
    a.name = name
    if isinstance(value, bool):
        a.type, a.i = P.AttributeProto.INT, int(value)
    elif isinstance(value, (int, np.integer)):
        a.type, a.i = P.AttributeProto.INT, int(value)
    elif isinstance(value, (float, np.floating)):
        a.type, a.f = P.AttributeProto.FLOAT, float(value)
    elif isinstance(value, str):
        a.type, a.s = P.AttributeProto.STRING, value.encode()
    elif isinstance(value, np.ndarray):
        a.type = P.AttributeProto.TENSOR
        a.t.CopyFrom(to_tensor_proto(name, value))
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], (float, np.floating)):
            a.type = P.AttributeProto.FLOATS
            a.floats.extend(float(v) for v in value)
        elif value and isinstance(value[0], str):
            a.type = P.AttributeProto.STRINGS
            a.strings.extend(v.encode() for v in value)
        else:
            a.type = P.AttributeProto.INTS
            a.ints.extend(int(v) for v in value)
    else:
        raise TypeError(f"unsupported attribute value {value!r}")
    return a


def save(model_proto: P.ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model_proto.SerializeToString())


def load(path: str) -> P.ModelProto:
    mp = P.ModelProto()
    with open(path, "rb") as f:
        mp.ParseFromString(f.read())
    return mp


# ===========================================================================
# Export: creator-graph walk → ONNX (reference: SingaFrontend)
# ===========================================================================
class _GraphBuilder:
    def __init__(self, graph: P.GraphProto):
        self.g = graph
        self._const_count = 0
        self._attn_masks = {}  # (Sq, Sk) -> shared causal-mask const

    def node(self, op_type: str, ins: Sequence[str], outs: Sequence[str],
             **attrs) -> P.NodeProto:
        n = self.g.node.add()
        n.op_type = op_type
        n.name = f"{op_type}_{len(self.g.node)}"
        n.input.extend(ins)
        n.output.extend(outs)
        for k, v in attrs.items():
            if v is not None:
                n.attribute.append(_make_attr(k, v))
        return n

    def const(self, arr, hint: str = "const") -> str:
        name = f"{hint}_{self._const_count}"
        self._const_count += 1
        self.g.initializer.append(to_tensor_proto(name, np.asarray(arr)))
        return name


# Plain one-to-one renames (no attributes).
_SIMPLE_EXPORT = {
    "ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh", "Tanh_": "Tanh",
    "Abs": "Abs", "Exp": "Exp", "Log": "Log", "Sqrt": "Sqrt",
    "Negative": "Neg", "Reciprocal": "Reciprocal", "Erf": "Erf",
    "Ceil": "Ceil", "Floor": "Floor", "Round": "Round", "Sign": "Sign",
    "Cos": "Cos", "Sin": "Sin", "Tan": "Tan", "Acos": "Acos",
    "Asin": "Asin", "Atan": "Atan", "Cosh": "Cosh", "Sinh": "Sinh",
    "Acosh": "Acosh", "Asinh": "Asinh", "Atanh": "Atanh",
    "SoftPlus": "Softplus", "SoftSign": "Softsign", "Gelu": "Gelu",
    "Add": "Add", "Sub": "Sub", "Mul": "Mul", "Div": "Div", "Pow": "Pow",
    "Minimum": "Min", "Maximum": "Max", "Less": "Less",
    "Greater": "Greater", "Equal": "Equal", "Mult": "MatMul",
    "GlobalAveragePool": "GlobalAveragePool", "Identity": "Identity",
}


def _export_node(op, in_names: List[str], out_names: List[str],
                 gb: _GraphBuilder, resolve=lambda t: None) -> None:
    cls = type(op).__name__
    if cls == "Square":
        gb.node("Mul", [in_names[0], in_names[0]], out_names)
    elif cls == "AddBias":
        if op.axis == 1:
            # x + b[:, None]: unsqueeze the bias so ONNX broadcasting
            # matches the per-row semantics.
            b2 = out_names[0] + "_bias2d"
            gb.node("Unsqueeze",
                    [in_names[1], gb.const(np.asarray([1], np.int64),
                                           "axes")], [b2])
            gb.node("Add", [in_names[0], b2], out_names)
        else:
            gb.node("Add", in_names, out_names)
    elif cls in _SIMPLE_EXPORT:
        gb.node(_SIMPLE_EXPORT[cls], in_names, out_names)
    elif cls in ("SoftMax", "LogSoftMax"):
        gb.node("Softmax" if cls == "SoftMax" else "LogSoftmax",
                in_names, out_names, axis=op.axis)
    elif cls == "Clip":
        ins = list(in_names)
        ins.append(gb.const(np.float32(op.min), "clip_min")
                   if op.min is not None else "")
        if op.max is not None:
            ins.append(gb.const(np.float32(op.max), "clip_max"))
        gb.node("Clip", ins, out_names)
    elif cls == "Elu":
        gb.node("Elu", in_names, out_names, alpha=op.alpha)
    elif cls == "SeLU":
        gb.node("Selu", in_names, out_names, alpha=op.alpha, gamma=op.gamma)
    elif cls == "LeakyRelu":
        gb.node("LeakyRelu", in_names, out_names, alpha=op.a)
    elif cls == "HardSigmoid":
        gb.node("HardSigmoid", in_names, out_names, alpha=op.alpha,
                beta=op.gamma)
    elif cls == "Cast":
        gb.node("Cast", in_names, out_names,
                to=int(_NP2ONNX[np.dtype(op.to)]))
    elif cls == "Gemm":
        gb.node("Gemm", in_names, out_names, alpha=op.alpha, beta=op.beta,
                transA=op.transA, transB=op.transB)
    elif cls == "Reshape":
        shape = gb.const(np.asarray(op.shape, np.int64), "shape")
        gb.node("Reshape", [in_names[0], shape], out_names)
    elif cls == "Flatten":
        gb.node("Flatten", in_names, out_names, axis=op.axis)
    elif cls == "Transpose":
        gb.node("Transpose", in_names, out_names, perm=op.axes)
    elif cls == "Concat":
        gb.node("Concat", in_names, out_names, axis=op.axis)
    elif cls == "Slice":
        ins = [in_names[0],
               gb.const(np.asarray(op.starts, np.int64), "starts"),
               gb.const(np.asarray(op.ends, np.int64), "ends"),
               gb.const(np.asarray(op.axes, np.int64), "axes"),
               gb.const(np.asarray(op.steps, np.int64), "steps")]
        gb.node("Slice", ins, out_names)
    elif cls == "SplitOp":
        gb.node("Split", [in_names[0],
                          gb.const(np.asarray(op.parts, np.int64), "split")],
                out_names, axis=op.axis)
    elif cls == "Gather":
        idx = gb.const(np.asarray(op.indices, np.int64), "indices")
        gb.node("Gather", [in_names[0], idx], out_names, axis=op.axis)
    elif cls == "Embedding":
        # Re-link the lookup to the live graph value feeding the
        # indices (usually the token-id input); bake only if untraceable.
        idx = resolve(getattr(op, "_indices_src", None))
        if idx is None:
            idx = gb.const(np.asarray(op.indices, np.int64), "indices")
        gb.node("Gather", [in_names[0], idx], out_names, axis=0)
    elif cls == "Tile":
        gb.node("Tile", [in_names[0],
                         gb.const(np.asarray(op.repeats, np.int64),
                                  "repeats")], out_names)
    elif cls == "Squeeze":
        ax = op.axis
        ins = [in_names[0]]
        if ax is not None:
            axes = [ax] if isinstance(ax, int) else list(ax)
            ins.append(gb.const(np.asarray(axes, np.int64), "axes"))
        gb.node("Squeeze", ins, out_names)
    elif cls == "Unsqueeze":
        gb.node("Unsqueeze",
                [in_names[0],
                 gb.const(np.asarray(op.axis, np.int64), "axes")], out_names)
    elif cls == "Pad":
        ins = [in_names[0], gb.const(np.asarray(op.pads, np.int64), "pads"),
               gb.const(np.float32(op.constant), "pad_value")]
        gb.node("Pad", ins, out_names, mode=op.mode)
    elif cls == "Expand":
        gb.node("Expand", [in_names[0],
                           gb.const(np.asarray(op.shape, np.int64),
                                    "shape")], out_names)
    elif cls == "DepthToSpace":
        gb.node("DepthToSpace", in_names, out_names, blocksize=op.b,
                mode=op.mode)
    elif cls == "SpaceToDepth":
        gb.node("SpaceToDepth", in_names, out_names, blocksize=op.b)
    elif cls == "Where":
        cond = gb.const(np.asarray(op.cond).astype(np.bool_), "cond")
        gb.node("Where", [cond] + list(in_names), out_names)
    elif cls == "OneHot":
        ins = [in_names[0],
               gb.const(np.asarray(op.depth, np.int64), "depth"),
               gb.const(np.asarray([0.0, 1.0], np.float32), "values")]
        gb.node("OneHot", ins, out_names, axis=op.axis)
    elif cls in ("ReduceSum",):
        ins = [in_names[0]]
        if op.axes is not None:
            ins.append(gb.const(np.asarray(op.axes, np.int64), "axes"))
        gb.node("ReduceSum", ins, out_names, keepdims=int(op.keepdims))
    elif cls in ("ReduceMean", "Max", "Min"):
        onnx_op = {"ReduceMean": "ReduceMean", "Max": "ReduceMax",
                   "Min": "ReduceMin"}[cls]
        gb.node(onnx_op, in_names, out_names, axes=op.axes,
                keepdims=int(op.keepdims))
    elif cls == "Dropout":
        gb.node("Dropout",
                [in_names[0], gb.const(np.float32(op.ratio), "ratio")],
                out_names)
    elif cls == "LayerNorm":
        gb.node("LayerNormalization", in_names, out_names, axis=-1,
                epsilon=op.eps)
    elif cls == "_Conv2d":
        h = op.handle
        ph, pw = h.padding
        gb.node("Conv", in_names, out_names, kernel_shape=h.kernel_size,
                strides=h.stride, pads=[ph, pw, ph, pw],
                dilations=h.dilation, group=h.groups)
    elif cls == "_Pooling2d":
        h = op.handle
        ph, pw = h.padding
        gb.node("MaxPool" if h.is_max else "AveragePool", in_names,
                out_names, kernel_shape=h.kernel_size, strides=h.stride,
                pads=[ph, pw, ph, pw])
    elif cls == "_BatchNorm2d":
        mean = gb.const(np.asarray(op.rm), "running_mean")
        var = gb.const(np.asarray(op.rv), "running_var")
        gb.node("BatchNormalization",
                list(in_names) + [mean, var], out_names,
                epsilon=op.handle.eps, momentum=1.0 - op.handle.factor)
    elif cls == "_ConvTranspose2d":
        h = op.handle
        ph, pw = h.padding
        gb.node("ConvTranspose", in_names, out_names,
                kernel_shape=h.kernel_size, strides=h.stride,
                pads=[ph, pw, ph, pw],
                output_padding=list(h.output_padding), group=h.groups)
    elif cls == "InstanceNorm":
        gb.node("InstanceNormalization", in_names, out_names,
                epsilon=op.eps)
    elif cls == "ScatterElements":
        ins = [in_names[0],
               gb.const(np.asarray(op.indices, np.int64), "indices"),
               gb.const(np.asarray(op.updates), "updates")]
        gb.node("ScatterElements", ins, out_names, axis=op.axis)
    elif cls == "Einsum":
        gb.node("Einsum", in_names, out_names, equation=op.equation)
    elif cls == "_RNN":
        _export_rnn(op, in_names, out_names, gb)
    elif cls == "Attention":
        _export_attention(op, in_names, out_names, gb)
    else:
        raise ValueError(
            f"sonnx export: op {cls} has no ONNX mapping "
            "(reference sonnx.py raises the same way for unsupported ops)")


def _export_attention(op, in_names, out_names, gb):
    """Decompose the fused Attention op (autograd.Attention over
    [B, H, S, D]) into the standard ONNX stream —
    Transpose/MatMul/Mul(scale)/Add(causal mask)/Softmax/MatMul —
    which is exactly how zoo transformers encode it, so the export
    re-imports through existing mappings with no custom op."""
    import math as _math

    q_t, k_t = op.inputs[0], op.inputs[1]
    sq, d = q_t.shape[2], q_t.shape[3]
    sk = k_t.shape[2]
    scale = op.scale if op.scale is not None else 1.0 / _math.sqrt(d)
    base = out_names[0]
    kt = f"{base}_kT"
    gb.node("Transpose", [in_names[1]], [kt], perm=[0, 1, 3, 2])
    s = f"{base}_scores"
    gb.node("MatMul", [in_names[0], kt], [s])
    ss = f"{base}_scaled"
    gb.node("Mul", [s, gb.const(np.asarray(scale, np.float32),
                                "attn_scale")], [ss])
    if op.causal:
        # query i attends keys j <= i (start-aligned, rectangular OK —
        # same mask plain_attention builds); exp(-1e9) underflows to
        # exactly 0, matching the fused kernel's masked softmax. One
        # shared initializer per (Sq, Sk): a per-layer copy would grow
        # the file by layers * Sq * Sk floats.
        memo = gb._attn_masks
        if (sq, sk) not in memo:
            mask = np.where(np.tril(np.ones((sq, sk), bool)),
                            0.0, -1e9).astype(np.float32)
            memo[(sq, sk)] = gb.const(mask, "causal_mask")
        sm = f"{base}_masked"
        gb.node("Add", [ss, memo[(sq, sk)]], [sm])
        ss = sm
    p = f"{base}_probs"
    gb.node("Softmax", [ss], [p], axis=-1)
    gb.node("MatMul", [p, in_names[2]], out_names)


def _export_rnn(op, in_names, out_names, gb):
    """Export the packed-blob `_RNN` op (ops/rnn.py) as a chain of
    ONNX LSTM/GRU/RNN nodes, one per layer — ONNX recurrent nodes are
    single-layer. The packed cuDNN-order blob is unpacked into the
    ONNX W/R/B initializers (inverse gate reorder); each layer's
    3-axis ONNX Y is transposed+reshaped back to our (S, B, nd*H)
    activation layout for the next layer / downstream consumers."""
    h = op.handle
    mode = h.mode
    onnx_op = {"lstm": "LSTM", "gru": "GRU",
               "tanh": "RNN", "relu": "RNN"}[mode]
    nd = h.num_directions
    L = h.num_layers
    hidden = h.hidden_size
    gh = h.num_gates * hidden
    perm = _RNN_GATE_PERM_INV[mode]
    seg = {k: np.asarray(v)
           for k, v in h.unpack(op.inputs[3].to_numpy()).items()}
    seq, batch, _ = op.inputs[0].shape
    zeros_b = np.zeros((gh,), np.float32)

    def init_state(name, li):
        """Per-layer [nd, B, H] slice of the (L*nd, B, H) state. Always
        a Slice NODE on the graph value — slicing a captured VALUE at
        export time would disconnect a declared h0/c0 graph input."""
        if not name:  # omitted (all-zero) state: ONNX default
            return ""
        if L == 1:
            return name
        sl = f"{name}_l{li}_slice"
        gb.node("Slice",
                [name, gb.const(np.asarray([li * nd], np.int64), "st"),
                 gb.const(np.asarray([(li + 1) * nd], np.int64), "en"),
                 gb.const(np.asarray([0], np.int64), "ax")], [sl])
        return sl

    cur = in_names[0]
    hys, cys = [], []
    for li in range(L):
        W = np.stack([_gate_reord(seg[("W_ih", li, d)], hidden, perm)
                      for d in range(nd)])
        R = np.stack([_gate_reord(seg[("W_hh", li, d)], hidden, perm)
                      for d in range(nd)])
        B = np.stack([np.concatenate([
            _gate_reord(seg.get(("b_ih", li, d), zeros_b), hidden, perm),
            _gate_reord(seg.get(("b_hh", li, d), zeros_b), hidden, perm)])
            for d in range(nd)])
        ins = [cur, gb.const(W, f"rnn_W_l{li}"),
               gb.const(R, f"rnn_R_l{li}"), gb.const(B, f"rnn_B_l{li}"),
               "", init_state(in_names[1], li)]
        if mode == "lstm":
            ins.append(init_state(in_names[2], li))
        y4 = f"{out_names[0]}_l{li}_y4"
        hy = f"{out_names[0]}_l{li}_hy"
        cy = f"{out_names[0]}_l{li}_cy"
        attrs = {"hidden_size": hidden,
                 "direction": "bidirectional" if nd == 2 else "forward"}
        if mode == "gru":
            attrs["linear_before_reset"] = 1
        if onnx_op == "RNN":
            attrs["activations"] = [mode.capitalize()] * nd
        gb.node(onnx_op, ins,
                [y4, hy] + ([cy] if mode == "lstm" else []), **attrs)
        hys.append(hy)
        if mode == "lstm":
            cys.append(cy)
        # ONNX Y (S, nd, B, H) -> our layer activation (S, B, nd*H)
        tr = f"{out_names[0]}_l{li}_tr"
        gb.node("Transpose", [y4], [tr], perm=[0, 2, 1, 3])
        nxt = (out_names[0] if li == L - 1
               else f"{out_names[0]}_l{li}_flat")
        gb.node("Reshape",
                [tr, gb.const(np.asarray([seq, batch, nd * hidden],
                                         np.int64), "yshape")], [nxt])
        cur = nxt

    def join(parts, out):
        if len(parts) == 1:
            gb.node("Identity", parts, [out])
        else:
            gb.node("Concat", parts, [out], axis=0)

    join(hys, out_names[1])
    if mode == "lstm":
        join(cys, out_names[2])
    else:
        # non-LSTM cy output is all-zero in our op; emit a matching
        # constant so the graph stays well-formed
        gb.node("Identity",
                [gb.const(np.zeros((L * nd, batch, hidden), np.float32),
                          "rnn_cy_zero")], [out_names[2]])


def _topo_ops(outputs: Sequence[Tensor]) -> List:
    seen, order, stack = set(), [], []
    for y in outputs:
        if y.creator is not None:
            stack.append((y.creator, False))
    while stack:
        op, done = stack.pop()
        if done:
            order.append(op)
            continue
        if id(op) in seen:
            continue
        seen.add(id(op))
        stack.append((op, True))
        for t in op.inputs:
            if t.creator is not None and id(t.creator) not in seen:
                stack.append((t.creator, False))
    return order


def to_onnx(model, inputs: Sequence[Tensor],
            model_name: str = "singa_tpu") -> P.ModelProto:
    """Export `model.forward(*inputs)` as an ONNX ModelProto.

    Reference: `SingaFrontend.to_onnx` / `sonnx.to_onnx(inputs, y)` —
    runs one eager forward to materialize the creator graph, then
    serializes it (graph mode is temporarily ignored; the exported
    graph is the same program).
    """
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    ins = list(inputs)
    saved_rg = [t.requires_grad for t in ins]
    for t in ins:
        t.requires_grad = True  # ensure creator links are recorded
    try:
        y = model.forward(*ins) if hasattr(model, "forward") else model(*ins)
    finally:
        for t, rg in zip(ins, saved_rg):
            t.requires_grad = rg
        if hasattr(model, "train") and was_training:
            model.train(True)
    outputs = list(y) if isinstance(y, (tuple, list)) else [y]

    mp = P.ModelProto()
    mp.ir_version = IR_VERSION
    mp.producer_name = "singa_tpu"
    op_set = mp.opset_import.add()
    op_set.domain = ""
    op_set.version = OPSET_VERSION
    g = mp.graph
    g.name = model_name
    gb = _GraphBuilder(g)

    topo = _topo_ops(outputs)
    # Packed RNN blobs are re-emitted by _export_rnn as the unpacked
    # ONNX W/R/B initializers; skip the blob param unless something
    # else also consumes it, or the weights ship twice.
    use_count: Dict[int, int] = {}
    rnn_w_ids = set()
    for op_ in topo:
        for i_, t_ in enumerate(op_.inputs):
            use_count[id(t_)] = use_count.get(id(t_), 0) + 1
            if type(op_).__name__ == "_RNN" and i_ == 3:
                rnn_w_ids.add(id(t_))
    rnn_w_only = {i_ for i_ in rnn_w_ids if use_count[i_] == 1}

    names: Dict[int, str] = {}
    if hasattr(model, "get_params"):
        for pname, pt in model.get_params().items():
            if id(pt) in rnn_w_only:
                continue
            names[id(pt)] = pname
            g.initializer.append(to_tensor_proto(pname, pt.to_numpy()))
    for i, t in enumerate(ins):
        names[id(t)] = f"input_{i}"
        vi = g.input.add()
        vi.name = f"input_{i}"
        vi.type.tensor_type.elem_type = _elem_type(t.dtype)
        for d in t.shape:
            vi.type.tensor_type.shape.dim.add().dim_value = d

    out_name: Dict[tuple, str] = {}

    def _in_name(t: Tensor) -> str:
        if t.creator is not None:
            return out_name[(id(t.creator), getattr(t, "creator_index", 0))]
        if id(t) in names:
            return names[id(t)]
        names[id(t)] = gb.const(t.to_numpy(), "capture")
        return names[id(t)]

    def _resolve(t) -> Optional[str]:
        if t is None:
            return None
        if t.creator is not None:
            return out_name.get(
                (id(t.creator), getattr(t, "creator_index", 0)))
        return names.get(id(t))

    def _rnn_omit(op, i, t):
        """Inputs of an `_RNN` op that must NOT materialize as graph
        values: the packed blob (re-emitted unpacked by _export_rnn)
        and all-zero captured initial states (ONNX's omitted-input
        default — emitting them as float initializers would let
        SONNXModel fine-tuning train what the native layer fixes at
        zero)."""
        if type(op).__name__ != "_RNN":
            return False
        if i == 3 and id(t) in rnn_w_only:
            return True
        if i in (1, 2) and t.creator is None and id(t) not in names:
            return not t.to_numpy().any()
        return False

    for op in topo:
        in_names = [("" if _rnn_omit(op, i, t) else _in_name(t))
                    for i, t in enumerate(op.inputs)]
        outs = []
        for i in range(op.num_outputs):
            nm = f"{op.name}_out{i}".replace("#", "_")
            out_name[(id(op), i)] = nm
            outs.append(nm)
        _export_node(op, in_names, outs, gb, resolve=_resolve)

    for i, t in enumerate(outputs):
        nm = (out_name[(id(t.creator), getattr(t, "creator_index", 0))]
              if t.creator is not None else _in_name(t))
        vo = g.output.add()
        vo.name = nm
        vo.type.tensor_type.elem_type = _elem_type(t.dtype)
        for d in t.shape:
            vo.type.tensor_type.shape.dim.add().dim_value = d
    return mp


# ===========================================================================
# Import: ONNX graph → autograd ops (reference: SingaBackend / SingaRep)
# ===========================================================================
class _ImportCtx:
    """Execution context: resolves node input names to live Tensors or
    compile-time constants (initializers / Constant nodes)."""

    def __init__(self, device):
        self.device = device
        self.values: Dict[str, Tensor] = {}
        self.consts: Dict[str, np.ndarray] = {}

    def tensor(self, name: str) -> Tensor:
        if name in self.values:
            return self.values[name]
        if name in self.consts:
            t = tensor_mod.from_numpy(
                np.asarray(self.consts[name]), device=self.device)
            self.values[name] = t
            return t
        raise KeyError(f"sonnx: undefined graph value {name!r}")

    def const(self, name: str) -> Optional[np.ndarray]:
        if name in self.consts:
            return self.consts[name]
        t = self.values.get(name)
        if t is not None and t.creator is None:
            return t.to_numpy()
        return None


def _sym_pads(node) -> tuple:
    """Decode ONNX pads [hb, wb, he, we] to the symmetric (ph, pw) the
    handles support; reject asymmetric padding / auto_pad rather than
    silently computing the wrong thing."""
    if _attr(node, "auto_pad", "NOTSET") not in ("NOTSET", ""):
        raise ValueError(
            f"sonnx: auto_pad is unsupported (node {node.op_type}); "
            "re-export with explicit pads")
    pads = list(_attr(node, "pads", [0, 0, 0, 0]))
    if len(pads) == 2:
        pads = pads * 2
    if pads[0] != pads[2] or pads[1] != pads[3]:
        raise ValueError(
            f"sonnx: asymmetric pads {pads} unsupported "
            f"(node {node.op_type})")
    return pads[0], pads[1]


def _pool_handle(node, is_max):
    ks = _attr(node, "kernel_shape")
    cip = bool(_attr(node, "count_include_pad", 0))
    return native.PoolingHandle(tuple(ks),
                                tuple(_attr(node, "strides", [1, 1])),
                                _sym_pads(node), is_max=is_max,
                                count_include_pad=cip)


def _import_conv(ctx, node):
    x = ctx.tensor(node.input[0])
    w = ctx.tensor(node.input[1])
    b = (ctx.tensor(node.input[2])
         if len(node.input) > 2 and node.input[2] else None)
    group = _attr(node, "group", 1)
    o, cpg, kh, kw = w.shape
    handle = native.ConvHandle(
        cpg * group, o, (kh, kw),
        stride=tuple(_attr(node, "strides", [1, 1])),
        padding=_sym_pads(node),
        dilation=tuple(_attr(node, "dilations", [1, 1])),
        groups=group, bias=b is not None)
    return autograd.conv2d(handle, x, w, b)


def _import_convtranspose(ctx, node):
    x = ctx.tensor(node.input[0])
    w = ctx.tensor(node.input[1])  # IOHW: (C_in, C_out/g, kh, kw)
    b = (ctx.tensor(node.input[2])
         if len(node.input) > 2 and node.input[2] else None)
    # Reject what the handle cannot represent rather than silently
    # computing the wrong shape (the _sym_pads convention).
    if list(_attr(node, "dilations", [1, 1])) != [1, 1]:
        raise ValueError("sonnx: ConvTranspose dilations != 1 "
                         "unsupported")
    if _attr(node, "output_shape") is not None:
        raise ValueError("sonnx: ConvTranspose output_shape is "
                         "unsupported; re-export with explicit pads/"
                         "output_padding")
    group = _attr(node, "group", 1)
    cin, cog, kh, kw = w.shape
    opads = tuple(_attr(node, "output_padding", [0, 0]))
    handle = native.ConvTransposeHandle(
        cin, cog * group, (kh, kw),
        stride=tuple(_attr(node, "strides", [1, 1])),
        padding=_sym_pads(node),
        output_padding=opads,
        groups=group, bias=b is not None)
    return autograd.conv_transpose2d(handle, x, w, b)


def _import_instancenorm(ctx, node):
    return autograd.InstanceNorm(_attr(node, "epsilon", 1e-5))(
        ctx.tensor(node.input[0]), ctx.tensor(node.input[1]),
        ctx.tensor(node.input[2]))


def _import_scatter(ctx, node):
    indices = ctx.const(node.input[1])
    updates = ctx.const(node.input[2])
    if indices is None or updates is None:
        raise ValueError(
            "sonnx: ScatterElements indices/updates must be "
            "constants/initializers")
    if _attr(node, "reduction", "none") != "none":
        raise ValueError("sonnx: ScatterElements reduction != 'none' "
                         "unsupported")
    return autograd.ScatterElements(
        indices, updates, _attr(node, "axis", 0))(
        ctx.tensor(node.input[0]))


def _import_einsum(ctx, node):
    return autograd.Einsum(_attr(node, "equation"))(
        *[ctx.tensor(i) for i in node.input])


def _import_bn(ctx, node):
    x = ctx.tensor(node.input[0])
    scale = ctx.tensor(node.input[1])
    bias = ctx.tensor(node.input[2])
    mean = ctx.tensor(node.input[3])
    var = ctx.tensor(node.input[4])
    handle = native.BatchNormHandle(
        factor=1.0 - _attr(node, "momentum", 0.9),
        eps=_attr(node, "epsilon", 1e-5))
    op = autograd._BatchNorm2d(handle, mean, var)
    y = op(x, scale, bias)
    # Training mode: rebind the updated running stats onto the live
    # mean/var tensors (the native layer does the same, layer.py
    # BatchNorm2d.forward) so fine-tuning moves them and graph-mode
    # captures them as state outputs.
    if autograd.training and op.new_running_mean is not None:
        mean.data = op.new_running_mean
        var.data = op.new_running_var
    return y


def _import_gemm(ctx, node):
    a = ctx.tensor(node.input[0])
    b = ctx.tensor(node.input[1])
    cs = ([ctx.tensor(node.input[2])] if len(node.input) > 2
          and node.input[2] else [])
    return autograd.Gemm(_attr(node, "alpha", 1.0),
                         _attr(node, "beta", 1.0),
                         _attr(node, "transA", 0),
                         _attr(node, "transB", 0))(a, b, *cs)


def _import_reshape(ctx, node):
    x = ctx.tensor(node.input[0])
    shape = _attr(node, "shape")
    if shape is None:
        shape = ctx.const(node.input[1])
        if shape is None:
            raise ValueError("sonnx: dynamic Reshape shape unsupported")
    shape = [int(s) for s in np.asarray(shape).ravel()]
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return autograd.reshape(x, shape)


def _req_const(ctx, node, idx, what) -> np.ndarray:
    c = ctx.const(node.input[idx])
    if c is None:
        raise ValueError(
            f"sonnx: {node.op_type} with a runtime-computed {what} is "
            "unsupported (must be a constant/initializer)")
    return c


def _import_slice(ctx, node):
    x = ctx.tensor(node.input[0])
    if len(node.input) > 1:
        starts = _req_const(ctx, node, 1, "starts").tolist()
        ends = _req_const(ctx, node, 2, "ends").tolist()
        axes = (_req_const(ctx, node, 3, "axes").tolist()
                if len(node.input) > 3 and node.input[3] else None)
        steps = (_req_const(ctx, node, 4, "steps").tolist()
                 if len(node.input) > 4 and node.input[4] else None)
    else:
        starts = _attr(node, "starts")
        ends = _attr(node, "ends")
        axes = _attr(node, "axes")
        steps = None
    return autograd.Slice(starts, ends, axes, steps)(x)


def _axes_arg(ctx, node, idx=1):
    if len(node.input) > idx and node.input[idx]:
        c = ctx.const(node.input[idx])
        return None if c is None else [int(v) for v in c.ravel()]
    a = _attr(node, "axes")
    return None if a is None else list(a)


def _import_cast(ctx, node):
    to = _ONNX2NP[_attr(node, "to")]
    return autograd.cast(ctx.tensor(node.input[0]), to)


def _import_dropout(ctx, node):
    # Inference-mode import: identity (reference backend does the same).
    return autograd.Identity()(ctx.tensor(node.input[0]))


def _import_layernorm(ctx, node):
    x = ctx.tensor(node.input[0])
    g = ctx.tensor(node.input[1])
    b = (ctx.tensor(node.input[2]) if len(node.input) > 2 and node.input[2]
         else tensor_mod.from_numpy(
             np.zeros(g.shape, np.float32), device=ctx.device))
    axis = _attr(node, "axis", -1)
    # Positive last-axis spellings (e.g. axis=2 on rank-3) are the same
    # computation; only genuinely non-last-axis normalization is refused.
    if axis is not None and axis % len(x.shape) != len(x.shape) - 1:
        raise ValueError(
            "sonnx: LayerNormalization only supports last-axis "
            f"normalization (got axis={axis} for rank {len(x.shape)})")
    return autograd.layer_norm(x, g, b, eps=_attr(node, "epsilon", 1e-5))


def _import_constant(ctx, node):
    val = _attr(node, "value")
    ctx.consts[node.output[0]] = np.asarray(val)
    return None


def _import_pad(ctx, node):
    x = ctx.tensor(node.input[0])
    mode = _attr(node, "mode", "constant")
    if len(node.input) > 1:
        pads = _req_const(ctx, node, 1, "pads").tolist()
        cval = (float(_req_const(ctx, node, 2, "value"))
                if len(node.input) > 2 and node.input[2] else 0.0)
    else:
        pads = _attr(node, "pads")
        cval = _attr(node, "value", 0.0)
    return autograd.Pad(mode, pads, cval)(x)


# ONNX <-> cuDNN recurrent gate orders. ONNX LSTM weights are iofc;
# our packed blob (ops/rnn.py) uses cuDNN ifgo. ONNX GRU is zrh; ours
# is rzn (linear_before_reset). Vanilla RNN has one gate (no reorder).
_RNN_GATE_PERM = {"lstm": [0, 2, 3, 1], "gru": [1, 0, 2],
                  "tanh": [0], "relu": [0]}
_RNN_GATE_PERM_INV = {"lstm": [0, 3, 1, 2], "gru": [1, 0, 2],
                      "tanh": [0], "relu": [0]}


def _gate_reord(a, hidden, perm):
    """Reorder the gate blocks of a (G*H, X) weight / (G*H,) bias."""
    g = len(perm)
    return a.reshape(g, hidden, -1)[perm].reshape(g * hidden, *a.shape[1:])


def _import_rnn_common(ctx, node, mode):
    from .ops.rnn import RNNHandle

    if _attr(node, "layout", 0) != 0:
        raise ValueError("sonnx: LSTM/GRU/RNN layout=1 is unsupported "
                         "(re-export seq-major)")
    if len(node.input) > 4 and node.input[4]:
        raise ValueError("sonnx: sequence_lens is unsupported")
    direction = _attr(node, "direction", "forward")
    if direction not in ("forward", "bidirectional"):
        raise ValueError(f"sonnx: direction {direction!r} unsupported")
    if mode == "gru" and _attr(node, "linear_before_reset", 0) != 1:
        raise ValueError("sonnx: GRU linear_before_reset=0 is "
                         "unsupported (this framework implements the "
                         "cuDNN/=1 semantics)")
    if _attr(node, "clip") is not None:
        raise ValueError("sonnx: recurrent `clip` attribute is "
                         "unsupported")
    if _attr(node, "input_forget", 0):
        raise ValueError("sonnx: LSTM input_forget=1 is unsupported")
    acts = _attr(node, "activations")
    if mode in ("tanh", "relu"):
        if acts:
            low = [a.lower() for a in acts]
            if any(a not in ("tanh", "relu") for a in low):
                raise ValueError(f"sonnx: RNN activations {acts!r} "
                                 "unsupported")
            if len(set(low)) > 1:
                raise ValueError(
                    "sonnx: per-direction RNN activations "
                    f"{acts!r} unsupported (one cell mode per node)")
            mode = low[0]
    elif acts:
        nd_acts = {"lstm": ["sigmoid", "tanh", "tanh"],
                   "gru": ["sigmoid", "tanh"]}[mode]
        want = nd_acts * (2 if direction == "bidirectional" else 1)
        if [a.lower() for a in acts] != want:
            raise ValueError("sonnx: non-default LSTM/GRU activations "
                             "unsupported")
    Wt = ctx.tensor(node.input[1])  # (nd, G*H, In)
    Rt = ctx.tensor(node.input[2])  # (nd, G*H, H)
    nd, gh, in_dim = Wt.shape
    hidden = int(_attr(node, "hidden_size", Rt.shape[-1]))
    Bt = (ctx.tensor(node.input[3])
          if len(node.input) > 3 and node.input[3] else None)
    perm = _RNN_GATE_PERM[mode]
    # Row indices realizing the gate-block permutation.
    rows = np.concatenate([np.arange(p * hidden, (p + 1) * hidden)
                           for p in perm]).astype(np.int32)

    handle = RNNHandle(in_dim, hidden, 1, mode=mode, bias=True,
                       bidirectional=(nd == 2))

    # The packed blob is BUILT THROUGH AUTOGRAD OPS (gather/slice/
    # reshape/concat) from the W/R/B tensors, so when those are
    # SONNXModel-registered params, fine-tuning gradients flow back
    # into them — a numpy repack would silently freeze the weights.
    # Piece order must equal RNNHandle._segments: per direction,
    # W_ih | W_hh | b_ih | b_hh.
    def take_dir(t, d, cols):
        td = autograd.reshape(autograd.Gather(0, np.asarray([d]))(t),
                              (gh, cols))
        return autograd.reshape(autograd.Gather(0, rows)(td),
                                (gh * cols,))

    pieces = []
    zeros_bias = None
    for d in range(nd):
        pieces.append(take_dir(Wt, d, in_dim))
        pieces.append(take_dir(Rt, d, hidden))
        if Bt is not None:
            bd = autograd.reshape(
                autograd.Gather(0, np.asarray([d]))(Bt), (2 * gh,))
            for lo, hi in ((0, gh), (gh, 2 * gh)):
                half = autograd.Slice([lo], [hi])(bd)
                pieces.append(autograd.Gather(0, rows)(half))
        else:
            if zeros_bias is None:
                zeros_bias = tensor_mod.from_numpy(
                    np.zeros((gh,), np.float32), device=ctx.device)
            pieces += [zeros_bias, zeros_bias]
    blob = autograd.Concat(0)(*pieces)

    x = ctx.tensor(node.input[0])
    seq, batch, _ = x.shape

    def state(idx):
        if len(node.input) > idx and node.input[idx]:
            return ctx.tensor(node.input[idx])
        return tensor_mod.from_numpy(
            np.zeros((nd, batch, hidden), np.float32), device=ctx.device)

    hx = state(5)
    cx = state(6)  # ignored by non-LSTM modes
    y, hy, cy = autograd.rnn_op(handle, x, hx, cx, blob)
    # ours: (S, B, nd*H) with [fwd|bwd] blocks -> ONNX Y (S, nd, B, H)
    y4 = autograd.transpose(
        autograd.reshape(y, (seq, batch, nd, hidden)), (0, 2, 1, 3))
    if mode == "lstm":
        return (y4, hy, cy)
    return (y4, hy)


def _import_where(ctx, node):
    cond = ctx.const(node.input[0])
    if cond is None:
        raise ValueError(
            "sonnx: Where with a runtime-computed condition is "
            "unsupported (condition must be a constant/initializer)")
    return autograd.Where(cond)(ctx.tensor(node.input[1]),
                                ctx.tensor(node.input[2]))


def _import_onehot(ctx, node):
    depth = ctx.const(node.input[1])
    values = ctx.const(node.input[2])
    if depth is None or values is None:
        raise ValueError("sonnx: OneHot depth/values must be constants")
    if not np.allclose(np.asarray(values).ravel(), [0.0, 1.0]):
        raise ValueError("sonnx: OneHot only supports values [0, 1]")
    return autograd.OneHot(int(np.asarray(depth).ravel()[0]),
                           _attr(node, "axis", -1))(
        ctx.tensor(node.input[0]))


def _simple(op_factory):
    return lambda ctx, node: op_factory()(
        *[ctx.tensor(i) for i in node.input if i])


_IMPORTERS = {
    "Relu": _simple(autograd.ReLU),
    "Sigmoid": _simple(autograd.Sigmoid),
    "Tanh": _simple(autograd.Tanh),
    "Abs": _simple(autograd.Abs),
    "Exp": _simple(autograd.Exp),
    "Log": _simple(autograd.Log),
    "Sqrt": _simple(autograd.Sqrt),
    "Neg": _simple(autograd.Negative),
    "Reciprocal": _simple(autograd.Reciprocal),
    "Erf": _simple(autograd.Erf),
    "Ceil": _simple(autograd.Ceil),
    "Floor": _simple(autograd.Floor),
    "Round": _simple(autograd.Round),
    "Sign": _simple(autograd.Sign),
    "Cos": _simple(autograd.Cos), "Sin": _simple(autograd.Sin),
    "Tan": _simple(autograd.Tan), "Acos": _simple(autograd.Acos),
    "Asin": _simple(autograd.Asin), "Atan": _simple(autograd.Atan),
    "Cosh": _simple(autograd.Cosh), "Sinh": _simple(autograd.Sinh),
    "Acosh": _simple(autograd.Acosh), "Asinh": _simple(autograd.Asinh),
    "Atanh": _simple(autograd.Atanh),
    "Softplus": _simple(autograd.SoftPlus),
    "Softsign": _simple(autograd.SoftSign),
    "Gelu": _simple(autograd.Gelu),
    "Identity": _simple(autograd.Identity),
    "Add": _simple(autograd.Add), "Sub": _simple(autograd.Sub),
    "Mul": _simple(autograd.Mul), "Div": _simple(autograd.Div),
    "Pow": _simple(autograd.Pow),
    "Min": _simple(autograd.Minimum), "Max": _simple(autograd.Maximum),
    "Less": _simple(autograd.Less), "Greater": _simple(autograd.Greater),
    "Equal": _simple(autograd.Equal),
    "MatMul": _simple(autograd.Mult),
    "GlobalAveragePool": _simple(autograd.GlobalAveragePool),
    "Softmax": lambda ctx, n: autograd.SoftMax(_attr(n, "axis", -1))(
        ctx.tensor(n.input[0])),
    "LogSoftmax": lambda ctx, n: autograd.LogSoftMax(_attr(n, "axis", -1))(
        ctx.tensor(n.input[0])),
    "Elu": lambda ctx, n: autograd.Elu(_attr(n, "alpha", 1.0))(
        ctx.tensor(n.input[0])),
    "Selu": lambda ctx, n: autograd.SeLU(
        _attr(n, "alpha", 1.67326), _attr(n, "gamma", 1.0507))(
        ctx.tensor(n.input[0])),
    "LeakyRelu": lambda ctx, n: autograd.LeakyRelu(
        _attr(n, "alpha", 0.01))(ctx.tensor(n.input[0])),
    "HardSigmoid": lambda ctx, n: autograd.HardSigmoid(
        _attr(n, "alpha", 0.2), _attr(n, "beta", 0.5))(
        ctx.tensor(n.input[0])),
    "Clip": lambda ctx, n: autograd.Clip(
        float(_req_const(ctx, n, 1, "min")) if len(n.input) > 1
        and n.input[1] else _attr(n, "min"),
        float(_req_const(ctx, n, 2, "max")) if len(n.input) > 2
        and n.input[2] else _attr(n, "max"))(ctx.tensor(n.input[0])),
    "Cast": _import_cast,
    "Gemm": _import_gemm,
    "Conv": _import_conv,
    "BatchNormalization": _import_bn,
    "MaxPool": lambda ctx, n: autograd.pooling_2d(
        _pool_handle(n, True), ctx.tensor(n.input[0])),
    "AveragePool": lambda ctx, n: autograd.pooling_2d(
        _pool_handle(n, False), ctx.tensor(n.input[0])),
    "Reshape": _import_reshape,
    "Flatten": lambda ctx, n: autograd.flatten(
        ctx.tensor(n.input[0]), _attr(n, "axis", 1)),
    "Transpose": lambda ctx, n: autograd.transpose(
        ctx.tensor(n.input[0]), _attr(n, "perm")),
    "Concat": lambda ctx, n: autograd.cat(
        [ctx.tensor(i) for i in n.input], _attr(n, "axis", 0)),
    "Slice": _import_slice,
    "Split": lambda ctx, n: autograd.SplitOp(
        _attr(n, "axis", 0),
        (_req_const(ctx, n, 1, "split sizes").tolist() if len(n.input) > 1
         else _attr(n, "split")))(ctx.tensor(n.input[0])),
    "Gather": lambda ctx, n: autograd.Gather(
        _attr(n, "axis", 0), ctx.tensor(n.input[1]))(ctx.tensor(n.input[0])),
    "Tile": lambda ctx, n: autograd.Tile(
        _req_const(ctx, n, 1, "repeats").tolist())(ctx.tensor(n.input[0])),
    "Squeeze": lambda ctx, n: autograd.Squeeze(
        _axes_arg(ctx, n))(ctx.tensor(n.input[0])),
    "Unsqueeze": lambda ctx, n: autograd.Unsqueeze(
        _axes_arg(ctx, n))(ctx.tensor(n.input[0])),
    "Pad": _import_pad,
    "LSTM": lambda ctx, n: _import_rnn_common(ctx, n, "lstm"),
    "GRU": lambda ctx, n: _import_rnn_common(ctx, n, "gru"),
    "RNN": lambda ctx, n: _import_rnn_common(ctx, n, "tanh"),
    "Expand": lambda ctx, n: autograd.Expand(
        _req_const(ctx, n, 1, "shape").tolist())(ctx.tensor(n.input[0])),
    "DepthToSpace": lambda ctx, n: autograd.DepthToSpace(
        _attr(n, "blocksize"), _attr(n, "mode", "DCR"))(
        ctx.tensor(n.input[0])),
    "SpaceToDepth": lambda ctx, n: autograd.SpaceToDepth(
        _attr(n, "blocksize"))(ctx.tensor(n.input[0])),
    "Where": _import_where,
    "OneHot": _import_onehot,
    "ReduceSum": lambda ctx, n: autograd.ReduceSum(
        _axes_arg(ctx, n), _attr(n, "keepdims", 1))(ctx.tensor(n.input[0])),
    "ReduceMean": lambda ctx, n: autograd.ReduceMean(
        _attr(n, "axes"), _attr(n, "keepdims", 1))(ctx.tensor(n.input[0])),
    "ReduceMax": lambda ctx, n: autograd.Max(
        _attr(n, "axes"), _attr(n, "keepdims", 1))(ctx.tensor(n.input[0])),
    "ReduceMin": lambda ctx, n: autograd.Min(
        _attr(n, "axes"), _attr(n, "keepdims", 1))(ctx.tensor(n.input[0])),
    "Dropout": _import_dropout,
    "LayerNormalization": _import_layernorm,
    "Constant": _import_constant,
    "ConvTranspose": _import_convtranspose,
    "InstanceNormalization": _import_instancenorm,
    "ScatterElements": _import_scatter,
    "Einsum": _import_einsum,
}


class SingaRep:
    """Executable imported graph. Reference: `sonnx.SingaRep` —
    `run(inputs)` returns output Tensors; execution goes through the
    autograd ops so results are differentiable."""

    def __init__(self, model_proto: P.ModelProto, device=None,
                 init_inputs: Optional[Sequence] = None):
        self.model_proto = model_proto
        self.device = device or get_default_device()
        g = model_proto.graph
        self.params: "OrderedDict[str, Tensor]" = OrderedDict()
        self._init_names = set()
        for tp in g.initializer:
            arr = to_numpy(tp)
            self._init_names.add(tp.name)
            t = tensor_mod.from_numpy(arr, device=self.device)
            self.params[tp.name] = t
        self.input_names = [vi.name for vi in g.input
                            if vi.name not in self._init_names]
        self.output_names = [vo.name for vo in g.output]
        self.nodes = list(g.node)
        unsupported = sorted({n.op_type for n in self.nodes
                              if n.op_type not in _IMPORTERS})
        if unsupported:
            raise ValueError(f"sonnx: unsupported ONNX ops {unsupported}")

    def run(self, inputs: Sequence) -> List[Tensor]:
        ctx = _ImportCtx(self.device)
        for name, t in self.params.items():
            ctx.values[name] = t
        if len(inputs) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs "
                f"({self.input_names}), got {len(inputs)}")
        for name, x in zip(self.input_names, inputs):
            if not isinstance(x, Tensor):
                x = tensor_mod.from_numpy(np.asarray(x), device=self.device)
            ctx.values[name] = x
        for node in self.nodes:
            out = _IMPORTERS[node.op_type](ctx, node)
            if out is None:  # Constant: registered as const
                continue
            outs = out if isinstance(out, tuple) else (out,)
            for name, t in zip(node.output, outs):
                ctx.values[name] = t
        return [ctx.tensor(n) for n in self.output_names]


class SingaBackend:
    """Reference: `sonnx.SingaBackend(onnx.backend.base.Backend)`."""

    @staticmethod
    def prepare(model_proto: P.ModelProto, device=None, **kwargs) -> SingaRep:
        return SingaRep(model_proto, device)


def prepare(model_proto, device=None, **kwargs) -> SingaRep:
    """Reference: `sonnx.prepare(model, device)`."""
    if isinstance(model_proto, (str, bytes)):
        model_proto = load(model_proto)
    return SingaBackend.prepare(model_proto, device, **kwargs)


class SONNXModel(model_mod.Model):
    """Reference: `sonnx.SONNXModel` — a `Model` over an imported ONNX
    graph; subclass and override `forward(self, *x)` (calling
    `super().forward`) and `train_one_batch` to fine-tune (the BERT
    workflow, SURVEY.md §3.4). Initializers become trainable params, so
    `compile(use_graph=True)` jits the imported graph like any native
    model, including mesh mode.
    """

    def __init__(self, onnx_model, device=None):
        super().__init__()
        if isinstance(onnx_model, (str, bytes)):
            onnx_model = load(onnx_model)
        # Exact graph digest for the AOT export cache: an imported
        # model's program is the ONNX graph, not Python source, so the
        # base topology_fingerprint (class source + param inventory)
        # could collide across two graphs with identical weights
        # inventory but different wiring.
        import hashlib

        self._onnx_digest = hashlib.sha256(
            onnx_model.SerializeToString()).hexdigest()
        self.rep = SingaRep(onnx_model, device)
        # BN running stats are state, not trainable params (the native
        # BatchNorm2d layer registers them the same way).
        stat_names = set()
        for node in self.rep.nodes:
            if node.op_type == "BatchNormalization":
                stat_names.update(node.input[3:5])
        self._onnx_param_names = {}
        for name, t in self.rep.params.items():
            if not np.issubdtype(np.dtype(t.dtype), np.floating):
                continue
            attr = "p_" + "".join(c if c.isalnum() else "_" for c in name)
            self._onnx_param_names[attr] = name
            if name in stat_names:
                self.register_state(attr, t)
            else:
                self.register_param(attr, t)

    def forward(self, *x, aux_output=()):
        outs = self.rep.run(list(x))
        aux = [self.rep.params[n] if n in self.rep.params else None
               for n in aux_output]
        if aux_output:
            return tuple(outs) + tuple(aux)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def train_one_batch(self, x, y):
        out = self.forward(x)
        out0 = out[0] if isinstance(out, tuple) else out
        loss = autograd.softmax_cross_entropy(out0, y)
        self._optimizer.backward_and_update(loss)
        return out, loss

    def input_specs(self):
        """Per-sample (shape, dtype) of every graph input, batch dim
        (dim 0) dropped — read from the graph's value-info, so the
        serving prewarm (`tools/prewarm.py --onnx`) can enumerate the
        (model, bucket) artifact grid without the operator re-typing
        shapes the model already declares. Inputs with no static shape
        info (or rank 0) are reported with shape None — the caller
        must supply those explicitly."""
        specs = []
        for vi in self.rep.model_proto.graph.input:
            if vi.name in self.rep._init_names:
                continue
            tt = vi.type.tensor_type
            dtype = np.dtype(_ONNX2NP.get(tt.elem_type, np.float32))
            dims = [d.dim_value for d in tt.shape.dim]
            if len(dims) < 1 or any(d <= 0 for d in dims[1:]):
                specs.append((None, str(dtype)))
            else:
                specs.append((tuple(int(d) for d in dims[1:]),
                              str(dtype)))
        return specs

    def topology_fingerprint(self) -> str:
        """AOT export-cache identity (ISSUE 6): everything the base
        fingerprint hashes (subclass source, param/state inventory,
        scalar config attrs — a fine-tune subclass's baked constants
        must key) PLUS the EXACT serialized ONNX graph, which the
        inventory alone cannot distinguish (two graphs can wire the
        same weights differently)."""
        import hashlib

        h = hashlib.sha256()
        h.update(super().topology_fingerprint().encode())
        h.update(self._onnx_digest.encode())
        return h.hexdigest()
