"""Convolution / BatchNorm / Pooling over XLA HLO.

Reference parity:
  - `src/model/operation/convolution.{h,cc}`: `ConvHandle`,
    `CudnnConvHandle`, `GpuConvForward/Backward{x,W,b}` → here one
    `ConvHandle` + `conv2d` via `lax.conv_general_dilated` (backward
    comes from `jax.vjp`, which XLA lowers to the transposed convs the
    reference hand-dispatches to cuDNN algos).
  - `src/model/operation/batchnorm.{h,cc}`: `BatchNormHandle`,
    `GpuBatchNormForwardTraining/Inference/Backward` → fused-in-XLA
    normalization; running-stat update semantics preserved
    (running = (1-momentum)*running + momentum*batch, cuDNN-style
    exponentialAverageFactor).
  - `src/model/operation/pooling.{h,cc}`: `PoolingHandle`,
    `GpuPoolingForward/Backward` max/avg → `lax.reduce_window`.

Layout: NCHW at the API (reference layout); XLA relayouts for the MXU
internally. Conv accumulates in fp32; input/filter dtype is whatever
the caller passes (bf16 under mixed-precision policy).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

_Pair = Union[int, Tuple[int, int]]


def _pair(v: _Pair) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


class ConvHandle:
    """Shape/config metadata for a 2-d convolution.

    Reference: `ConvHandle` / `CudnnConvHandle` (algo selection and
    workspace fields dropped — XLA owns algorithm choice).
    """

    def __init__(
        self,
        in_channels: int,
        out_channels: int,
        kernel_size: _Pair,
        stride: _Pair = 1,
        padding: _Pair = 0,
        dilation: _Pair = 1,
        groups: int = 1,
        bias: bool = True,
    ):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.dilation = _pair(dilation)
        self.groups = groups
        self.bias = bias
        if in_channels % groups or out_channels % groups:
            raise ValueError(
                f"channels ({in_channels}->{out_channels}) not divisible by groups={groups}"
            )

    def out_shape(self, h: int, w: int) -> Tuple[int, int]:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        dh, dw = self.dilation
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        return oh, ow


@partial(jax.jit, static_argnums=(0, 3), inline=True)
def _conv2d_nobias(handle: ConvHandle, x, w, precision):
    ph, pw = handle.padding
    # fp32 operands: force fp32 accumulation explicitly. bf16 (AMP):
    # omit preferred_element_type — the MXU still accumulates fp32
    # internally, and jax 0.9's conv transpose rule rejects mixed
    # cotangent/operand dtypes when preferred != operand dtype.
    pref = jnp.float32 if x.dtype == jnp.float32 else None
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=handle.stride,
        padding=((ph, ph), (pw, pw)),
        rhs_dilation=handle.dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=handle.groups,
        preferred_element_type=pref,
        precision=precision,
    ).astype(x.dtype)


def conv2d(handle: ConvHandle, x, w, b=None):
    """Reference: `GpuConvForward(x, W, b, handle)`.

    x: (N, C, H, W); w: (O, C/groups, kh, kw); b: (O,) or None.
    Under the AMP policy (`tensor.set_compute_dtype`), operands cast to
    bf16 at this boundary (fp32 MXU accumulation via
    preferred_element_type) and the output stays bf16.
    """
    from .. import tensor as tensor_mod

    x, w, b = tensor_mod.amp_cast(x, w, b)
    # Without an explicit precision, TPU lowers fp32 convs to bf16
    # passes (~1e-4 rel error) and the CPU-vs-TPU loss-parity gate
    # fails; thread the same policy the matmul ops use. Static jit arg
    # so a policy change retraces.
    y = _conv2d_nobias(handle, x, w, tensor_mod.get_matmul_precision())
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


class ConvTransposeHandle:
    """Config for 2-d transposed convolution (ONNX ConvTranspose;
    reference: the cuDNN backward-data path the reference reuses for
    deconvolution). Weight layout is ONNX/torch IOHW:
    (in_channels, out_channels // groups, kh, kw)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, output_padding=0, groups=1,
                 bias=True):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.output_padding = _pair(output_padding)
        self.groups = groups
        self.bias = bias


@partial(jax.jit, static_argnums=(0, 3), inline=True)
def _conv_transpose2d_nobias(handle: ConvTransposeHandle, x, w,
                             precision):
    """Transposed conv as an input-dilated conv with the flipped,
    IO-swapped kernel — the same lowering XLA uses for conv input
    gradients, so it rides the MXU like a forward conv."""
    g = handle.groups
    cin, cog, kh, kw = w.shape
    # IOHW -> OIHW per group, spatial flip
    wg = w.reshape(g, cin // g, cog, kh, kw)
    wg = jnp.transpose(wg, (0, 2, 1, 3, 4))
    w2 = wg.reshape(g * cog, cin // g, kh, kw)[:, :, ::-1, ::-1]
    ph, pw = handle.padding
    oph, opw = handle.output_padding
    pad = ((kh - 1 - ph, kh - 1 - ph + oph),
           (kw - 1 - pw, kw - 1 - pw + opw))
    pref = jnp.float32 if x.dtype == jnp.float32 else None
    return lax.conv_general_dilated(
        x, w2,
        window_strides=(1, 1),
        padding=pad,
        lhs_dilation=handle.stride,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=g,
        preferred_element_type=pref,
        precision=precision,
    ).astype(x.dtype)


def conv_transpose2d(handle: ConvTransposeHandle, x, w, b=None):
    """x: (N, C_in, H, W); w: (C_in, C_out/groups, kh, kw)."""
    from .. import tensor as tensor_mod

    x, w, b = tensor_mod.amp_cast(x, w, b)
    y = _conv_transpose2d_nobias(handle, x, w,
                                 tensor_mod.get_matmul_precision())
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y



def _at_least_f32(x):
    """Upcast low-precision inputs so normalization statistics are
    computed in at-least-fp32 (bf16 AMP stats must not drift), while
    f64 passes through (the numerical gradient audit's path)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


def _bn_stats_cast(x):
    """BatchNorm statistics precision: promote to the configured floor
    (`device.set_bn_stats_dtype`). Default floor fp32 reproduces
    `_at_least_f32`; a bf16 floor keeps bf16-AMP activations bf16
    through the whole normalization — no fp32 copy round-tripping HBM
    (the byte-diet lever). Promotion only: fp32/f64 inputs are never
    downcast, whatever the floor."""
    from .. import stats as stats_mod

    d = stats_mod.bn_stats_dtype()
    floor = jnp.float32 if d is None else jnp.dtype(d)
    return x.astype(jnp.promote_types(x.dtype, floor))


def instance_norm(x, scale, bias, eps: float = 1e-5):
    """ONNX InstanceNormalization: per-(N, C) normalization over the
    spatial dims; scale/bias are per-channel. Statistics in
    at-least-fp32 (matches the BN policy under AMP)."""
    axes = tuple(range(2, x.ndim))
    xf = _at_least_f32(x)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    shape = [1, -1] + [1] * (x.ndim - 2)
    y = (xf - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) \
        + bias.reshape(shape)
    return y.astype(x.dtype)


class BatchNormHandle:
    """Reference: `BatchNormHandle` / `CudnnBatchNormHandle`.

    `factor` is cuDNN's exponentialAverageFactor (SINGA passes the
    layer momentum): running = (1-factor)*running + factor*batch.
    """

    def __init__(self, factor: float = 0.9, eps: float = 1e-5):
        self.factor = factor
        self.eps = eps


def batchnorm_training(handle: BatchNormHandle, x, scale, bias, running_mean, running_var):
    """Reference: `GpuBatchNormForwardTraining`.

    Per-channel (axis 1) normalization over (N, H, W). Returns
    (y, batch_mean, batch_var, new_running_mean, new_running_var);
    batch stats are returned because the reference caches them for
    backward (here `jax.vjp` handles that, but the layer still updates
    running state from them).
    """
    axes = tuple(i for i in range(x.ndim) if i != 1)
    # The normalized output returns to x's dtype so bf16 activations
    # stay bf16 through BN; stats math happens at the configured
    # precision floor (_bn_stats_cast — fp32 by default, the compute
    # dtype under the byte-diet policy).
    xf = _bn_stats_cast(x)
    mean = jnp.mean(xf, axis=axes)
    # cuDNN uses biased variance for normalization.
    var = jnp.var(xf, axis=axes)
    shape = [1, -1] + [1] * (x.ndim - 2)
    inv = lax.rsqrt((var + handle.eps).astype(xf.dtype)).reshape(shape)
    y = ((xf - mean.reshape(shape).astype(xf.dtype)) * inv
         * scale.reshape(shape).astype(xf.dtype)
         + bias.reshape(shape).astype(xf.dtype)).astype(x.dtype)
    f = handle.factor
    # Running-stat STORAGE keeps its existing dtype (C-sized arrays,
    # negligible bytes) — only the batch-stat math dropped precision.
    new_rm = ((1.0 - f) * running_mean
              + f * mean.astype(running_mean.dtype))
    new_rv = ((1.0 - f) * running_var
              + f * var.astype(running_var.dtype))
    return y, mean, var, new_rm, new_rv


def batchnorm_inference(handle: BatchNormHandle, x, scale, bias, running_mean, running_var):
    """Reference: `GpuBatchNormForwardInference`."""
    shape = [1, -1] + [1] * (x.ndim - 2)
    xf = _bn_stats_cast(x)
    inv = lax.rsqrt((running_var + handle.eps).astype(xf.dtype)
                    ).reshape(shape)
    y = (xf - running_mean.reshape(shape).astype(xf.dtype)) * inv \
        * scale.reshape(shape).astype(xf.dtype) \
        + bias.reshape(shape).astype(xf.dtype)
    return y.astype(x.dtype)


class PoolingHandle:
    """Reference: `PoolingHandle` / `CudnnPoolingHandle`."""

    def __init__(
        self,
        kernel_size: _Pair,
        stride: _Pair = None,
        padding: _Pair = 0,
        is_max: bool = True,
        count_include_pad: bool = False,
    ):
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride) if stride is not None else self.kernel_size
        self.padding = _pair(padding)
        self.is_max = is_max
        self.count_include_pad = count_include_pad

    def out_shape(self, h: int, w: int) -> Tuple[int, int]:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        return (h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1


@partial(jax.jit, static_argnums=(0,), inline=True)
def pooling(handle: PoolingHandle, x):
    """Reference: `GpuPoolingForward` (max/avg) → `lax.reduce_window`."""
    kh, kw = handle.kernel_size
    sh, sw = handle.stride
    ph, pw = handle.padding
    window = (1, 1, kh, kw)
    strides = (1, 1, sh, sw)
    pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if handle.is_max:
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if handle.count_include_pad or (ph == 0 and pw == 0):
        return s / (kh * kw)
    # Divide by the true (unpadded) window size per position.
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, window, strides, pads
    )
    return s / counts


# PoolingHandle/ConvHandle/BatchNormHandle participate in jit static args;
# give them stable hash/eq by config so executable caching works.
def _cfg(obj):
    return tuple(sorted((k, v) for k, v in vars(obj).items()))


for _cls in (ConvHandle, BatchNormHandle, PoolingHandle):
    _cls.__hash__ = lambda self: hash((type(self).__name__, _cfg(self)))
    _cls.__eq__ = lambda self, other: (
        type(self) is type(other) and _cfg(self) == _cfg(other)
    )
