"""Hand-written Pallas TPU kernels for the fused/odd ops.

Reference parity: `src/core/tensor/math_kernel.cu` (SURVEY.md N10) —
the reference's hand-written CUDA kernels for ops that don't decompose
well into library calls. SURVEY §7 plans exactly this tier for TPU:
"hand-written Pallas kernels for the fused/odd ones (softmax-xent,
dropout, top-K sparsification) registered as custom-calls". These are
those kernels:

  * `softmax_xent` — fused log-softmax + NLL with a custom-VJP Pallas
    backward (KernelSoftmaxCrossEntropy / KernelSoftmaxCrossEntropyBwd
    equivalents). One HBM round-trip for the whole loss instead of
    separate softmax / gather / reduce programs; the backward
    recomputes probs in-VMEM (no softmax residual in HBM).
  * `dropout` — mask generation with the TPU's on-core PRNG
    (pltpu.prng_random_bits) fused with the scale-and-mask multiply
    (KernelDropout equivalent).
  * `topk_threshold` + `threshold_mask` — top-K gradient
    sparsification (the reference's `sparsification(topK=true)`,
    src/io/communicator.cc): a block-accumulated |g| histogram kernel
    picks a conservative threshold (keeps >= K elements; exact K
    requires a global sort), and a mask kernel zeroes the rest.

Enablement: `enable(True)` or SINGA_TPU_PALLAS=1 — consumers
(`autograd.SoftMaxCrossEntropy`, `dist.Communicator.sparsification`)
check `enabled()`. On non-TPU backends the kernels run in Pallas
interpret mode, so the CPU test suite covers them; on the chip they
compile to Mosaic.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports cleanly on CPU-only installs as well
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_ENABLED = os.environ.get("SINGA_TPU_PALLAS", "0") == "1"

# Per-kernel policy (VERDICT r4 next #3: "make every Pallas kernel pay
# or cut it").  Measured on the v5e (benchmarks/PALLAS_BENCH.md):
# fused softmax-xent wins at every tested shape (1.07-1.80x) and flash
# attention wins from seq >= ~1024 (1.14-1.27x; 0.98x at 512), so the
# default tier routes ONLY those, with the attention crossover
# enforced by `attn_supported`.  The on-core-PRNG dropout (0.94x) and
# the histogram top-K sparsifier (0.89-1.03x) sit at parity with
# XLA's own fusion — they remain correct, tested, and available, but
# engage only with SINGA_TPU_PALLAS_ALL=1 (or `enable_all`) so the
# default tier never trades a measured win for a measured loss.
_ALL = os.environ.get("SINGA_TPU_PALLAS_ALL", "0") == "1"
# ALL implies the tier itself: opting into the parity kernels with
# only SINGA_TPU_PALLAS_ALL=1 must not be a silent no-op.
_ENABLED = _ENABLED or _ALL
# Tuning knobs (exercised by benchmarks/pallas_tune.py on the chip):
_ATTN_MIN_SEQ = int(os.environ.get("SINGA_TPU_ATTN_MIN_SEQ", "1024"))


def enable(flag: bool = True) -> None:
    """Switch the Pallas kernel tier on/off (SINGA_TPU_PALLAS env also
    works)."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def enable_all(flag: bool = True) -> None:
    """Also route the parity-with-XLA kernels (dropout, top-K
    sparsify) through Pallas — off by default; see the policy note.
    Enabling ALL enables the tier itself (never a silent no-op);
    disabling ALL leaves the tier's own switch untouched."""
    global _ALL, _ENABLED
    _ALL = bool(flag)
    if _ALL:
        _ENABLED = True


def dropout_enabled() -> bool:
    return _ENABLED and _ALL


def sparsify_enabled() -> bool:
    return _ENABLED and _ALL


def _interpret() -> bool:
    """Interpret mode off-TPU so CI covers the kernel code paths."""
    return jax.default_backend() not in ("tpu", "axon")


_ROW_BUDGET = int(os.environ.get("SINGA_TPU_ROW_BUDGET", str(1 << 19)))
_HIST_BUDGET = int(os.environ.get("SINGA_TPU_HIST_BUDGET", str(1 << 13)))


def _row_tile(batch: int, ncol: int, budget: int = 0) -> int:
    """Rows per block: keep a block under ~budget elements, multiple
    of 8 (f32 sublane)."""
    budget = budget or _ROW_BUDGET
    rows = max(1, budget // max(ncol, 1))
    rows = min(batch, rows)
    if rows >= 8:
        rows -= rows % 8
    return max(rows, 1)


# ===========================================================================
# Fused softmax cross-entropy (forward + backward)
# ===========================================================================
def _xent_fwd_kernel(x_ref, lab_ref, loss_ref):
    x = x_ref[...].astype(jnp.float32)
    lab = lab_ref[...]  # (TILE_B, 1) int32
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    classes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x_lab = jnp.sum(jnp.where(classes == lab, x, 0.0), axis=-1,
                    keepdims=True)
    # Out-of-range labels (e.g. -1 padding) match the jnp path's
    # one_hot semantics: all-zero row -> zero loss contribution.
    valid = (lab >= 0) & (lab < x.shape[-1])
    loss_ref[...] = jnp.where(valid, jnp.log(s) + m - x_lab, 0.0)


def _xent_bwd_kernel(x_ref, lab_ref, g_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    lab = lab_ref[...]
    g = g_ref[...]  # (TILE_B, 1) upstream grad per row
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    classes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (classes == lab).astype(jnp.float32)
    # Same validity mask as the forward: padding rows (label -1 or
    # out-of-range) produced zero loss, so they get zero gradient.
    valid = (lab >= 0) & (lab < x.shape[-1])
    dx_ref[...] = jnp.where(valid, (p - onehot) * g,
                            0.0).astype(dx_ref.dtype)


def _pad_rows(a, tile):
    b = a.shape[0]
    pad = (-b) % tile
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
    return a, b


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_xent(logits, labels):
    """Per-row cross-entropy loss, fused. logits (B, C) float,
    labels (B,) int -> (B,) float32. Mean/scale is the caller's."""
    loss, _ = _softmax_xent_fwd(logits, labels)
    return loss


def _softmax_xent_fwd(logits, labels):
    b, c = logits.shape
    tile = _row_tile(b, c)
    lab2 = labels.reshape(b, 1).astype(jnp.int32)
    xp, b0 = _pad_rows(logits, tile)
    lp, _ = _pad_rows(lab2, tile)
    grid = (xp.shape[0] // tile,)
    loss = pl.pallas_call(
        _xent_fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0)),
                  pl.BlockSpec((tile, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        interpret=_interpret(),
    )(xp, lp)
    return loss[:b0, 0], (logits, labels)


def _softmax_xent_bwd(res, g):
    logits, labels = res
    b, c = logits.shape
    tile = _row_tile(b, c)
    lab2 = labels.reshape(b, 1).astype(jnp.int32)
    g2 = g.reshape(b, 1).astype(jnp.float32)
    xp, b0 = _pad_rows(logits, tile)
    lp, _ = _pad_rows(lab2, tile)
    gp, _ = _pad_rows(g2, tile)
    grid = (xp.shape[0] // tile,)
    dx = pl.pallas_call(
        _xent_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(xp.shape, logits.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0)),
                  pl.BlockSpec((tile, 1), lambda i: (i, 0)),
                  pl.BlockSpec((tile, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, c), lambda i: (i, 0)),
        interpret=_interpret(),
    )(xp, lp, gp)
    return dx[:b0], None


softmax_xent.defvjp(_softmax_xent_fwd, _softmax_xent_bwd)


# ===========================================================================
# Fused dropout (TPU on-core PRNG + mask + scale in one pass)
# ===========================================================================
def _dropout_kernel(seed_ref, x_ref, out_ref, mask_ref, *, keep):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.prng_random_bits(x_ref.shape)
    # uint32 -> uniform [0,1): take the top 24 bits.
    u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    mask = (u < keep).astype(x_ref.dtype) / keep
    mask_ref[...] = mask
    out_ref[...] = x_ref[...] * mask


def dropout(x, ratio: float, seed) -> tuple:
    """Fused dropout. Returns (y, mask/keep) — mask is what backward
    multiplies by (matches autograd.Dropout's cached mask semantics).
    `seed`: int32 scalar; each grid block reseeds with (seed, block)."""
    keep = 1.0 - float(ratio)
    orig_shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    pad = (-n) % lane
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(-1, lane)
    tile = _row_tile(x2.shape[0], lane)
    x2, r0 = _pad_rows(x2, tile)
    grid = (x2.shape[0] // tile,)
    seed_arr = jnp.asarray([seed], jnp.int32)
    y2, m2 = pl.pallas_call(
        functools.partial(_dropout_kernel, keep=keep),
        out_shape=(jax.ShapeDtypeStruct(x2.shape, x.dtype),
                   jax.ShapeDtypeStruct(x2.shape, x.dtype)),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM
                               if pltpu else None),
                  pl.BlockSpec((tile, lane), lambda i: (i, 0))],
        out_specs=(pl.BlockSpec((tile, lane), lambda i: (i, 0)),
                   pl.BlockSpec((tile, lane), lambda i: (i, 0))),
        interpret=_interpret(),
    )(seed_arr, x2)
    y = y2.reshape(-1)[:n].reshape(orig_shape)
    m = m2.reshape(-1)[:n].reshape(orig_shape)
    return y, m


# ===========================================================================
# Top-K sparsification: histogram threshold + mask
# ===========================================================================
_BINS = 512


_HIST_CHUNK = 128  # bins counted per inner iteration (one lane row)


def _hist_kernel(x_ref, gmax_ref, hist_ref):
    # Revisiting-output accumulation: every grid step maps to the SAME
    # (_BINS/_HIST_CHUNK, _HIST_CHUNK) output block; zero it first,
    # then add this block's histogram of |x| over linear bins in
    # [0, gmax]. Bins are processed _HIST_CHUNK at a time so the
    # one-hot intermediate stays (n, 128) — VMEM-safe for any block
    # size — instead of a full (n, _BINS) expansion.
    @pl.when(pl.program_id(0) == 0)
    def _():
        hist_ref[...] = jnp.zeros_like(hist_ref)

    a = jnp.abs(x_ref[...].astype(jnp.float32)).reshape(-1)
    gmax = gmax_ref[0]
    scale = jnp.where(gmax > 0, _BINS / gmax, 0.0)
    idx = jnp.clip((a * scale).astype(jnp.int32), 0, _BINS - 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (a.shape[0], _HIST_CHUNK),
                                    1)

    def chunk(c, _):
        base = c * _HIST_CHUNK
        counts = jnp.sum((lane + base == idx[:, None])
                         .astype(jnp.float32), axis=0)
        hist_ref[pl.dslice(c, 1), :] = (hist_ref[pl.dslice(c, 1), :]
                                        + counts[None, :])
        return 0

    jax.lax.fori_loop(0, _BINS // _HIST_CHUNK, chunk, 0)


def _mask_kernel(x_ref, thr_ref, out_ref):
    x = x_ref[...]
    thr = thr_ref[0]
    out_ref[...] = jnp.where(jnp.abs(x) >= thr, x, jnp.zeros_like(x))


def topk_threshold(flat, k: int):
    """Conservative top-K |g| threshold via a block-accumulated
    histogram (keeps >= k elements; all elements sharing the
    threshold bin survive — exact K would need a global sort, which
    the reference's encoder also avoids for large grads)."""
    n = flat.shape[0]
    gmax = jnp.max(jnp.abs(flat)).astype(jnp.float32)
    lane = 128
    pad = (-n) % lane
    x = jnp.pad(flat, (0, pad)) if pad else flat
    x2 = x.reshape(-1, lane)
    tile = _row_tile(x2.shape[0], lane, budget=_HIST_BUDGET)
    x2, _ = _pad_rows(x2, tile)
    grid = (x2.shape[0] // tile,)
    nrows = _BINS // _HIST_CHUNK
    hist = pl.pallas_call(
        _hist_kernel,
        out_shape=jax.ShapeDtypeStruct((nrows, _HIST_CHUNK),
                                       jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((tile, lane), lambda i: (i, 0)),
                  pl.BlockSpec(memory_space=pltpu.SMEM
                               if pltpu else None)],
        out_specs=pl.BlockSpec((nrows, _HIST_CHUNK), lambda i: (0, 0)),
        interpret=_interpret(),
    )(x2, jnp.asarray([1.0], jnp.float32) * gmax)
    # padding contributed zeros into bin 0; remove them
    hist = hist.reshape(_BINS).at[0].add(-(pad + (x2.size - x.size)))
    # threshold = lower edge of the first bin (from the top) where the
    # running count reaches k
    from_top = jnp.cumsum(hist[::-1])
    bin_from_top = jnp.argmax(from_top >= k)
    lower_edge = (_BINS - 1 - bin_from_top).astype(jnp.float32) \
        * gmax / _BINS
    return jnp.where(gmax > 0, lower_edge, jnp.float32(0.0))


def threshold_mask(x, thr):
    """Zero everything with |x| < thr (the sparsification select)."""
    orig = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    pad = (-n) % lane
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(-1, lane)
    tile = _row_tile(x2.shape[0], lane)
    x2, _ = _pad_rows(x2, tile)
    grid = (x2.shape[0] // tile,)
    y2 = pl.pallas_call(
        _mask_kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((tile, lane), lambda i: (i, 0)),
                  pl.BlockSpec(memory_space=pltpu.SMEM
                               if pltpu else None)],
        out_specs=pl.BlockSpec((tile, lane), lambda i: (i, 0)),
        interpret=_interpret(),
    )(x2, jnp.asarray(thr, jnp.float32).reshape(1))
    return y2.reshape(-1)[:n].reshape(orig)


# ===========================================================================
# Fused (flash-style) attention: softmax(qk^T/sqrt(d)) v with the score
# matrix living only in VMEM — never materialized to HBM. Forward saves
# just the per-row logsumexp; both backward kernels recompute the
# probabilities in-VMEM (the flash attention recipe). MXU does the four
# matmuls; padding and causality are iota masks.
# ===========================================================================
_ATTN_TQ = int(os.environ.get("SINGA_TPU_ATTN_TQ", "128"))
# query rows per grid step; env knob for tuning.  Validate HERE: a
# misaligned tile would otherwise surface as an opaque Mosaic
# BlockSpec rejection deep inside jit.
if _ATTN_TQ < 8 or _ATTN_TQ % 8:
    raise ValueError(
        f"SINGA_TPU_ATTN_TQ={_ATTN_TQ}: the flash-attention query "
        "tile must be a positive multiple of 8 (f32 sublane)")
_ATTN_VMEM_BUDGET = 6 * (1 << 20)  # bytes of k/v/q residents per head


def attn_supported(s: int, d: int) -> bool:
    """Route attention through the fused kernel only where it WINS:
    the head's K/V (and in backward, Q and dO) must fit the VMEM
    residency budget, and the sequence must clear the measured
    XLA crossover (~1024 on v5e — at 512 the kernel is 0.98x XLA;
    benchmarks/PALLAS_BENCH.md).  Long-context runs use ring
    attention anyway."""
    return (s >= _ATTN_MIN_SEQ
            and 4 * s * d * 4 <= _ATTN_VMEM_BUDGET)


def _attn_mask(scores, qi0, tq, sq, sk, causal):
    tq_, s_ = scores.shape
    qi = qi0 * tq + jax.lax.broadcasted_iota(jnp.int32, (tq_, s_), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (tq_, s_), 1)
    mask = (ki < sk) & (qi < sq)
    if causal:
        mask &= ki <= qi
    return jnp.where(mask, scores, -1e30)


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                     causal, sq, sk):
    q = q_ref[0].astype(jnp.float32) * scale      # (TQ, D)
    k = k_ref[0].astype(jnp.float32)              # (S, D)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = _attn_mask(s, pl.program_id(1), q.shape[0], sq, sk, causal)
    m = jnp.max(s, -1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, -1, keepdims=True)
    o_ref[0] = ((e / l) @ v).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _attn_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                    dq_ref, *, scale, causal, sq, sk):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = _attn_mask(s, pl.program_id(1), q.shape[0], sq, sk, causal)
    p = jnp.exp(s - lse_ref[0])                   # (TQ, S)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - dl_ref[0])
    dq_ref[0] = ((ds @ k) * scale).astype(dq_ref.dtype)


def _attn_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref,
                     dk_ref, dv_ref, *, scale, causal, sq, sk):
    k = k_ref[0].astype(jnp.float32)              # (TK, D)
    v = v_ref[0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32)              # (S, D) full
    do = do_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    # mask transposed relative to fwd: rows are queries, cols this k blk
    tk = k.shape[0]
    s_full = s.shape[0]
    qi = jax.lax.broadcasted_iota(jnp.int32, (s_full, tk), 0)
    ki = pl.program_id(1) * tk + jax.lax.broadcasted_iota(
        jnp.int32, (s_full, tk), 1)
    mask = (ki < sk) & (qi < sq)
    if causal:
        mask &= ki <= qi
    s = jnp.where(mask, s, -1e30)
    p = jnp.exp(s - lse_ref[0])                   # (S, TK)
    dv_ref[0] = jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - dl_ref[0])                     # (S, TK)
    dk_ref[0] = (jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale).astype(dk_ref.dtype)


def _attn_shapes(q):
    b, h, s, d = q.shape
    # Block dims must be sublane-aligned for the input dtype (f32: 8,
    # bf16: 16, int8: 32 — use 32 to cover all) or Mosaic rejects the
    # BlockSpec at lowering.
    tq = _ATTN_TQ if s >= _ATTN_TQ else -(-s // 32) * 32
    spad = -(-s // tq) * tq
    return b, h, s, d, tq, spad


def _attn_pad(x, spad):
    b, h, s, d = x.shape
    if s == spad:
        return x.reshape(b * h, s, d)
    return jnp.pad(x, ((0, 0), (0, 0), (0, spad - s), (0, 0))) \
        .reshape(b * h, spad, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, scale=None):
    """Fused attention over [B, H, S, D]; same semantics as
    `parallel.ring_attention.plain_attention`."""
    o, _ = _flash_fwd(q, k, v, causal, scale)
    return o


def _flash_fwd(q, k, v, causal, scale):
    b, h, s, d, tq, spad = _attn_shapes(q)
    sc = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    qp, kp, vp = (_attn_pad(x, spad) for x in (q, k, v))
    grid = (b * h, spad // tq)
    o, lse = pl.pallas_call(
        functools.partial(_attn_fwd_kernel, scale=sc, causal=causal,
                          sq=s, sk=s),
        out_shape=(jax.ShapeDtypeStruct((b * h, spad, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, spad, 1), jnp.float32)),
        grid=grid,
        in_specs=[pl.BlockSpec((1, tq, d), lambda i, j: (i, j, 0)),
                  pl.BlockSpec((1, spad, d), lambda i, j: (i, 0, 0)),
                  pl.BlockSpec((1, spad, d), lambda i, j: (i, 0, 0))],
        out_specs=(pl.BlockSpec((1, tq, d), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, tq, 1), lambda i, j: (i, j, 0))),
        interpret=_interpret(),
    )(qp, kp, vp)
    o = o.reshape(b, h, spad, d)[:, :, :s]
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    b, h, s, d, tq, spad = _attn_shapes(q)
    sc = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    # delta_i = rowsum(dO_i * O_i) — the flash-bwd softmax correction
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    qp, kp, vp, gp = (_attn_pad(x, spad) for x in (q, k, v, g))
    dpad = jnp.pad(delta, ((0, 0), (0, 0), (0, spad - s), (0, 0))) \
        .reshape(b * h, spad, 1) if spad != s else \
        delta.reshape(b * h, spad, 1)
    grid = (b * h, spad // tq)
    blk = lambda i, j: (i, j, 0)       # noqa: E731
    full = lambda i, j: (i, 0, 0)      # noqa: E731
    dq = pl.pallas_call(
        functools.partial(_attn_dq_kernel, scale=sc, causal=causal,
                          sq=s, sk=s),
        out_shape=jax.ShapeDtypeStruct((b * h, spad, d), q.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((1, tq, d), blk),
                  pl.BlockSpec((1, spad, d), full),
                  pl.BlockSpec((1, spad, d), full),
                  pl.BlockSpec((1, tq, d), blk),
                  pl.BlockSpec((1, tq, 1), blk),
                  pl.BlockSpec((1, tq, 1), blk)],
        out_specs=pl.BlockSpec((1, tq, d), blk),
        interpret=_interpret(),
    )(qp, kp, vp, gp, lse, dpad)
    dk, dv = pl.pallas_call(
        functools.partial(_attn_dkv_kernel, scale=sc, causal=causal,
                          sq=s, sk=s),
        out_shape=(jax.ShapeDtypeStruct((b * h, spad, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, spad, d), v.dtype)),
        grid=grid,
        in_specs=[pl.BlockSpec((1, tq, d), blk),
                  pl.BlockSpec((1, tq, d), blk),
                  pl.BlockSpec((1, spad, d), full),
                  pl.BlockSpec((1, spad, d), full),
                  pl.BlockSpec((1, spad, 1), full),
                  pl.BlockSpec((1, spad, 1), full)],
        out_specs=(pl.BlockSpec((1, tq, d), blk),
                   pl.BlockSpec((1, tq, d), blk)),
        interpret=_interpret(),
    )(kp, vp, qp, gp, lse, dpad)
    unpad = lambda x: x.reshape(b, h, spad, d)[:, :, :s]  # noqa: E731
    return unpad(dq), unpad(dk), unpad(dv)


flash_attention.defvjp(
    lambda q, k, v, causal, scale: _flash_fwd(q, k, v, causal, scale),
    _flash_bwd)


def topk_sparsify(x, spars: float):
    """Keep the ~top spars-fraction of |x| (reference:
    `fusedSparsification(topK=true)`), zeroing the rest."""
    flat = x.reshape(-1)
    k = max(1, int(flat.shape[0] * spars))
    thr = topk_threshold(flat, k)
    return threshold_mask(x, thr)
