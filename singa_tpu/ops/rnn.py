"""Recurrent ops: packed-weight RNN/LSTM/GRU as XLA while-loops.

Reference parity: `src/model/operation/rnn.{h,cc}` — `CudnnRNNHandle`
(LSTM/GRU/tanh/relu modes, packed weight blob, dropout between layers,
bidirectional), `GpuRNNForwardTraining/Inference`, `GpuRNNBackward{x,W}`.

TPU-native redesign (SURVEY.md §7 "hard parts" #2): cuDNN's fused RNN
becomes a `lax.scan` over time per layer. The packed-weight-blob API
edge is kept: one flat 1-D parameter vector per RNN, with a documented
layout so checkpoints are a single named array like the reference's.

Packing layout (per layer ℓ, per direction d, concatenated flat,
layers outermost, direction inner):

    W_ih (G*H, in_dim) | W_hh (G*H, H) | b_ih (G*H,) | b_hh (G*H,)

where G = gates-per-cell (1 for tanh/relu, 4 for LSTM, 3 for GRU) and
gate order follows cuDNN: LSTM = (i, f, g, o); GRU = (r, z, n) with
*linear-before-reset* semantics, n = tanh(Wn x + bWn + r ⊙ (Rn h + bRn))
— the cuDNN/ONNX convention, required for Char-RNN loss parity.

Performance: the input projection x·W_ihᵀ for the WHOLE sequence is a
single large batched matmul hoisted out of the scan (MXU-friendly);
only the h·W_hhᵀ recurrence runs inside the loop.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_GATES = {"relu": 1, "tanh": 1, "lstm": 4, "gru": 3}


class RNNHandle:
    """Reference: `CudnnRNNHandle` → `TpuRNNHandle`.

    Carries static configuration + the packed-weight layout table.
    """

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        num_layers: int = 1,
        mode: str = "lstm",
        bias: bool = True,
        dropout: float = 0.0,
        bidirectional: bool = False,
    ):
        mode = mode.lower()
        if mode not in _GATES:
            raise ValueError(f"mode must be one of {list(_GATES)}, got {mode!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.mode = mode
        self.bias = bias
        self.dropout = float(dropout)
        self.bidirectional = bidirectional
        self.num_directions = 2 if bidirectional else 1
        self.num_gates = _GATES[mode]
        # Offset table for the packed blob (static python ints).
        self._segments = []  # (name, layer, direction, shape, offset)
        off = 0
        g, h = self.num_gates, hidden_size
        for layer in range(num_layers):
            in_dim = input_size if layer == 0 else h * self.num_directions
            for d in range(self.num_directions):
                for name, shape in (
                    ("W_ih", (g * h, in_dim)),
                    ("W_hh", (g * h, h)),
                    ("b_ih", (g * h,)),
                    ("b_hh", (g * h,)),
                ):
                    if not bias and name.startswith("b"):
                        continue
                    self._segments.append((name, layer, d, shape, off))
                    off += int(np.prod(shape))
        self.weights_size = off

    # -- packed blob helpers ----------------------------------------------
    def unpack(self, w):
        """Packed 1-D blob → {(name, layer, dir): array} dict."""
        out = {}
        for name, layer, d, shape, off in self._segments:
            n = int(np.prod(shape))
            out[(name, layer, d)] = w[off:off + n].reshape(shape)
        return out

    def pack(self, tensors) -> jnp.ndarray:
        """Inverse of `unpack` (host-side; used by tests/converters)."""
        parts = []
        for name, layer, d, shape, _ in self._segments:
            parts.append(jnp.asarray(tensors[(name, layer, d)]).reshape(-1))
        return jnp.concatenate(parts) if parts else jnp.zeros((0,))

    def init_weights(self, key, dtype=jnp.float32) -> jnp.ndarray:
        """cuDNN-style default init: U(-1/sqrt(H), 1/sqrt(H)) for every
        segment (matches the reference's and torch's RNN init)."""
        k = 1.0 / np.sqrt(self.hidden_size)
        return jax.random.uniform(
            key, (self.weights_size,), dtype, minval=-k, maxval=k
        )

    def state_shape(self, batch: int) -> Tuple[int, int, int]:
        return (self.num_layers * self.num_directions, batch, self.hidden_size)

    # Value equality over the static config: handles are jit static
    # arguments (`rnn_forward` static_argnums), so identity hashing
    # would force a full XLA retrace for every freshly-built handle —
    # e.g. the sonnx importer builds one per SingaRep.run().
    def _config(self):
        return (self.input_size, self.hidden_size, self.num_layers,
                self.mode, self.bias, self.dropout, self.bidirectional)

    def __eq__(self, other):
        return (type(other) is type(self)
                and self._config() == other._config())

    def __hash__(self):
        return hash(self._config())


# ---------------------------------------------------------------------------
# Cell steps (h·W_hhᵀ inside scan; x projections precomputed outside)
# ---------------------------------------------------------------------------
def _mm(a, b):
    """Matmul under the framework precision policy (fp32 'highest' by
    default — TPU would otherwise run these in bf16 passes and the
    Char-RNN cross-backend loss parity drifts)."""
    from .. import tensor as tensor_mod

    return jnp.matmul(a, b, precision=tensor_mod.get_matmul_precision())


def _lstm_step(xw, h, c, W_hh, b_hh):
    g = xw + _mm(h, W_hh.T) + b_hh
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    gg = jnp.tanh(gg)
    c = f * c + i * gg
    h = o * jnp.tanh(c)
    return h, c


def _gru_step(xw, h, W_hh, b_hh):
    hw = _mm(h, W_hh.T) + b_hh  # linear BEFORE reset (cuDNN convention)
    xr, xz, xn = jnp.split(xw, 3, axis=-1)
    hr, hz, hn = jnp.split(hw, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1.0 - z) * n + z * h


def _plain_step(xw, h, W_hh, b_hh, act):
    return act(xw + _mm(h, W_hh.T) + b_hh)


def _scan_direction(handle: RNNHandle, mode, xs_proj, h0, c0, W_hh, b_hh,
                    reverse: bool):
    """Scan one (layer, direction) over time. xs_proj: (T, B, G*H)."""
    act = jnp.tanh if mode == "tanh" else jax.nn.relu

    if mode == "lstm":
        def step(carry, xw):
            h, c = carry
            h, c = _lstm_step(xw, h, c, W_hh, b_hh)
            return (h, c), h

        (hT, cT), ys = lax.scan(step, (h0, c0), xs_proj, reverse=reverse)
        return ys, hT, cT
    if mode == "gru":
        def step(h, xw):
            h = _gru_step(xw, h, W_hh, b_hh)
            return h, h
    else:
        def step(h, xw):
            h = _plain_step(xw, h, W_hh, b_hh, act)
            return h, h

    hT, ys = lax.scan(step, h0, xs_proj, reverse=reverse)
    return ys, hT, None


@partial(jax.jit, static_argnums=(0, 5), inline=True)
def rnn_forward(handle: RNNHandle, x, hx, cx, w, training: bool = False,
                dropout_key=None):
    """Reference: `GpuRNNForwardTraining/Inference`.

    x: (T, B, input_size) — seq-major like cuDNN/SINGA.
    hx: (L*D, B, H); cx: same (LSTM only, else ignored).
    w: packed 1-D blob (`handle.weights_size`).
    Returns (y, hy, cy): y is (T, B, D*H); cy is zeros for non-LSTM.
    """
    seg = handle.unpack(w)
    L, D, H, G = (handle.num_layers, handle.num_directions,
                  handle.hidden_size, handle.num_gates)
    zeros_b = jnp.zeros((G * H,), x.dtype)
    inp = x
    hys, cys = [], []
    for layer in range(L):
        outs = []
        for d in range(D):
            W_ih = seg[("W_ih", layer, d)]
            W_hh = seg[("W_hh", layer, d)]
            b_ih = seg.get(("b_ih", layer, d), zeros_b)
            b_hh = seg.get(("b_hh", layer, d), zeros_b)
            # Hoisted input projection: one (T*B, in)×(in, G*H) matmul.
            xs_proj = _mm(inp, W_ih.T) + b_ih
            idx = layer * D + d
            h0 = hx[idx]
            c0 = cx[idx] if handle.mode == "lstm" else None
            ys, hT, cT = _scan_direction(
                handle, handle.mode, xs_proj, h0, c0, W_hh, b_hh,
                reverse=(d == 1),
            )
            outs.append(ys)
            hys.append(hT)
            cys.append(cT if cT is not None else jnp.zeros_like(hT))
        inp = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if training and handle.dropout > 0 and layer < L - 1:
            assert dropout_key is not None, "dropout requires an rng key"
            lkey = jax.random.fold_in(dropout_key, layer)
            keep = 1.0 - handle.dropout
            mask = jax.random.bernoulli(lkey, keep, inp.shape)
            inp = jnp.where(mask, inp / keep, 0.0).astype(inp.dtype)
    hy = jnp.stack(hys)
    cy = jnp.stack(cys)
    return inp, hy, cy
