"""Device abstraction for the TPU-native framework.

Reference parity: SINGA's `include/singa/core/device.h` /
`src/core/device/device.cc` (`Device`, `CppCPU`, `CudaGPU`, `Platform`).
The reference routes every tensor op through
`Device::Exec(fn, read_blocks, write_blocks)`, which either runs the
lambda immediately (eager) or buffers it into a `Graph` for later
`Graph::Run()` (graph mode).

TPU-native redesign: XLA already *is* a buffering/fusing scheduler, so
`TpuDevice` does not reimplement SINGA's block-level graph. Eager ops
dispatch straight to jax (async, per-op compiled+cached by XLA); "graph
mode" is realized one level up, in `model.Model.compile(use_graph=True)`,
which traces the entire train step into a single `jax.jit` program —
the idiomatic XLA equivalent of SINGA's `Graph::Run()` replay
(SURVEY.md §1 "eager-by-default, graph-by-opt-in").

What *is* kept from the reference Device API:
  - `SetRandSeed` — counter-based RNG (threefry) replaces curand.
  - `Sync` — fences the device stream (was `cudaStreamSynchronize`).
  - `EnableGraph`/`graph_enabled` — consulted by `Model.compile`.
  - `SetVerbosity`/`PrintTimeProfiling`/`SetSkipIteration` — the per-op
    profiling table (reference: cudaEvent timing inside `Graph::Run`,
    `src/core/scheduler/scheduler.cc`); here backed by op-level wall
    timing in eager mode, and in graph (jit) mode by measured step
    times plus a per-HLO-instruction cost breakdown of the compiled
    program (`hlo_profile.py`) — fused regions are attributed back to
    framework ops via `jax.named_scope` metadata.
"""
from __future__ import annotations

import collections
import os
import time
from typing import Optional

import jax
import numpy as np

__all__ = [
    "Device",
    "CppCPU",
    "TpuDevice",
    "Platform",
    "create_cpu_device",
    "create_tpu_device",
    "create_tpu_device_on",
    "create_replica_device",
    "create_tpu_devices",
    "get_default_device",
    "enable_lazy_alloc",  # no-op parity shim
    # Eager hot-path config (singa_tpu.stats owns the state):
    "set_dag_cache_capacity",
    "set_dag_cache_policy",
    "set_buffer_donation",
    "get_eager_config",
    # Byte-diet knobs (ISSUE 2): BN statistics precision, recorded-
    # backward auto-route threshold, XLA flag profiles.
    "set_bn_stats_dtype",
    "set_dag_auto_flops_per_op",
    "set_xla_profile",
    "get_xla_profile",
    # Int8 quantized inference (ISSUE 19): the byte-diet on the
    # decode/forward path (singa_tpu.quant reads it).
    "set_inference_quant",
    # Resilience knobs (ISSUE 3): step guard + dynamic loss scaling
    # (singa_tpu.resilience owns the state/counters).
    "set_step_guard",
    "set_loss_scaling",
    # Microbatched gradient accumulation (ISSUE 4).
    "set_grad_accum",
    # Multi-axis parallel trainer (ISSUE 10; parallel.plan owns the
    # state).
    "set_parallel_plan",
    # Scan-level rematerialization policy (ISSUE 9; singa_tpu.stats
    # owns the state, model._JitStep reads it at build time).
    "set_remat_policy",
    # Observability (ISSUE 5): span tracer + device-profiler window
    # (singa_tpu.trace owns the state).
    "set_tracing",
    # AOT export cache + shape bucketing (ISSUE 6; singa_tpu.
    # export_cache owns the state).
    "set_export_cache",
    "set_shape_buckets",
    # Continuous-batching serving tier (ISSUE 7; singa_tpu.serve owns
    # the state) + its resilience layer (ISSUE 8).
    "set_serving",
    "set_serving_resilience",
    "set_decode_serving",
    "set_fleet",
    # Migration aliases (reference names):
    "create_cuda_gpu",
    "create_cuda_gpu_on",
    "create_cuda_gpus",
]


class Device:
    """Base device. Reference: `singa::Device` (include/singa/core/device.h).

    Each instance wraps one `jax.Device` and owns a counter-based RNG
    key stream (replacing the reference's per-device curand generator).
    """

    _next_uid = 0

    def __init__(self, jax_device, lang: str):
        self.jax_device = jax_device
        self.lang = lang  # "cpp" | "tpu"  (reference: kCpp / kCuda / kOpencl)
        self.id = getattr(jax_device, "id", 0)
        self.uid = Device._next_uid
        Device._next_uid += 1
        # Commit the key to this device so every op that consumes it
        # (and therefore every random fill) executes HERE — an
        # uncommitted key would drag CPU-tensor RNG onto the default
        # accelerator.
        self._rng_key = jax.device_put(jax.random.PRNGKey(0), jax_device)
        # Graph-capture flag, consulted by Model.compile (reference:
        # Device::EnableGraph / graph_enabled_).
        self._graph_enabled = False
        # Profiling state (reference: Device::SetVerbosity /
        # PrintTimeProfiling / SetSkipIteration).
        self._verbosity = 0
        self._skip_iteration = 5
        self._op_times = collections.defaultdict(lambda: [0.0, 0])
        self._iteration = 0
        # Graph-mode profiles: label -> {"rows": [...], "step_s": float}
        # (filled by model._JitStep when verbosity > 0; see
        # hlo_profile.py for the cost model).
        self._graph_profiles = {}

    # ---- RNG ------------------------------------------------------------
    def SetRandSeed(self, seed: int) -> None:
        """Reference: `Device::SetRandSeed` (curand seed → threefry key)."""
        self._rng_key = jax.device_put(jax.random.PRNGKey(seed),
                                       self.jax_device)

    set_rand_seed = SetRandSeed

    def next_key(self):
        """Split and return a fresh PRNG key (counter-based,
        reproducible).  The split runs under compile-time eval: the
        key is host state, so even inside a trace (the eval_shape init
        forward, a jitted init) it advances CONCRETELY — a traced key
        could never be handed back to host-side consumers."""
        with jax.ensure_compile_time_eval():
            self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    # ---- Execution ------------------------------------------------------
    def put(self, array):
        """Place a host array onto this device (async)."""
        return jax.device_put(array, self.jax_device)

    def Sync(self) -> None:
        """Fence: block until all prior work on this device is done.

        Reference: `CudaGPU::Sync` → `cudaStreamSynchronize`. A bare
        device_put is NOT a fence (transfers ride a separate stream);
        instead enqueue a trivial *execution* — PJRT executes programs
        on a device in FIFO submission order — and block on its result.
        """
        x = jax.device_put(np.zeros((), np.float32), self.jax_device)
        _sync_kernel(x).block_until_ready()

    sync = Sync

    # ---- Graph-mode flag -------------------------------------------------
    def EnableGraph(self, flag: bool) -> None:
        """Reference: `Device::EnableGraph`. Consulted by Model.compile."""
        self._graph_enabled = bool(flag)

    @property
    def graph_enabled(self) -> bool:
        return self._graph_enabled

    # ---- Profiling -------------------------------------------------------
    def SetVerbosity(self, v: int) -> None:
        self._verbosity = int(v)

    def SetSkipIteration(self, k: int) -> None:
        self._skip_iteration = int(k)

    def StepIteration(self) -> None:
        self._iteration += 1

    def RecordOpTime(self, name: str, seconds: float) -> None:
        if self._verbosity > 0 and self._iteration >= self._skip_iteration:
            t = self._op_times[name]
            t[0] += seconds
            t[1] += 1

    def TimeOp(self, name: str):
        """Context manager timing one op when verbosity > 0."""
        return _OpTimer(self, name)

    def PrintTimeProfiling(self) -> str:
        """Reference: `Device::PrintTimeProfiling` — per-op time table.

        Eager ops report measured wall times; graph (jit) runs report
        the measured step time plus the compiled program's per-op XLA
        cost breakdown (hlo_profile.py)."""
        lines = ["Time Profiling:"]
        total = sum(t for t, _ in self._op_times.values())
        for name, (t, n) in sorted(
            self._op_times.items(), key=lambda kv: -kv[1][0]
        ):
            avg_us = (t / max(n, 1)) * 1e6
            pct = 100.0 * t / total if total else 0.0
            lines.append(
                f"  OP = {name:<28} Time = {avg_us:10.3f} us x {n:<6d} ({pct:5.1f}%)"
            )
        out = "\n".join(lines)
        for label, prof in self._graph_profiles.items():
            from . import hlo_profile

            out += f"\n[{label}]\n" + hlo_profile.format_table(
                prof["rows"], prof.get("step_s"))
        print(out)
        return out

    def ResetTimeProfiling(self) -> None:
        self._op_times.clear()
        self._graph_profiles.clear()
        self._iteration = 0

    # ---- Misc ------------------------------------------------------------
    def __repr__(self):
        return f"<{type(self).__name__} id={self.id} lang={self.lang}>"


@jax.jit
def _sync_kernel(x):
    return x + 1


class _OpTimer:
    def __init__(self, dev: Device, name: str):
        self.dev, self.name = dev, name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.dev.RecordOpTime(self.name, time.perf_counter() - self.t0)
        return False


class CppCPU(Device):
    """Host CPU device. Reference: `singa::CppCPU` (src/core/device/cpp_cpu.cc)."""

    def __init__(self, jax_device=None):
        if jax_device is None:
            # Local, not global: under multi-controller launch
            # (train_multiprocess/train_mpi), jax.devices() lists other
            # processes' devices too, and the host device must be one
            # this process can address.
            jax_device = jax.local_devices(backend="cpu")[0]
        super().__init__(jax_device, lang="cpp")


class TpuDevice(Device):
    """TPU device backed by XLA/PJRT-managed HBM buffers.

    This is the north-star component: the reference's `CudaGPU`
    (src/core/device/cuda_gpu.cc: cnmem pool + cublas/cudnn/curand
    handles + stream) re-imagined for TPU. There is no custom memory
    pool — PJRT owns HBM (SURVEY.md §7: "no custom allocator") — and no
    handle zoo — XLA compiles and caches per-op executables.
    """

    def __init__(self, jax_device):
        super().__init__(jax_device, lang="tpu")


class Platform:
    """Device discovery/factory.

    Reference: `singa::Platform` (src/core/device/platform.cc) —
    `GetNumGPUs`, `CreateCudaGPUs`, `DeviceQuery`. Here: enumerate
    PJRT devices; TPU when available, else CPU.
    """

    _cache: dict = {}

    @staticmethod
    def GetNumTPUs() -> int:
        try:
            return len(_backend_devices("tpu"))
        except RuntimeError:
            return 0

    # Reference-name alias so `Platform.GetNumGPUs()` keeps working.
    GetNumGPUs = GetNumTPUs

    @staticmethod
    def GetNumCPUs() -> int:
        return len(_backend_devices("cpu"))

    @staticmethod
    def CreateTpuDevices(num: int):
        devs = _accel_devices()
        if len(devs) < num:
            raise ValueError(
                f"requested {num} accelerator devices, only {len(devs)} present"
            )
        return [Platform._get(TpuDevice, d) for d in devs[:num]]

    CreateCudaGPUs = CreateTpuDevices

    @staticmethod
    def CreateTpuDeviceOn(device_id: int):
        devs = _accel_devices()
        for d in devs:
            if d.id == device_id:
                return Platform._get(TpuDevice, d)
        raise ValueError(f"no accelerator device with id {device_id}")

    @staticmethod
    def DeviceQuery(device_id: int = 0) -> str:
        devs = jax.devices()
        lines = [f"{len(devs)} device(s):"]
        for d in devs:
            lines.append(
                f"  id={d.id} platform={d.platform} kind={getattr(d, 'device_kind', '?')}"
            )
        return "\n".join(lines)

    @staticmethod
    def _get(cls, jax_device):
        key = (cls.__name__, jax_device.id, jax_device.platform)
        if key not in Platform._cache:
            Platform._cache[key] = cls(jax_device)
        return Platform._cache[key]


def _backend_devices(platform: str):
    return jax.devices(platform)


def _accel_devices():
    """Accelerator devices: real TPUs if present, else the CPU backend's
    (possibly virtual, via --xla_force_host_platform_device_count) devices.
    The CPU fallback is what makes the whole stack CI-testable."""
    for platform in ("tpu", "axon"):
        try:
            devs = jax.devices(platform)
            if devs:
                return devs
        except RuntimeError:
            continue
    return jax.devices()


_default_device: Optional[Device] = None


def get_default_device() -> Device:
    """Reference: `Platform::GetDefaultDevice` — the host CppCPU."""
    global _default_device
    if _default_device is None:
        _default_device = CppCPU()
    return _default_device


def create_cpu_device() -> CppCPU:
    return get_default_device()


def create_tpu_device() -> Device:
    """First accelerator device (TPU if present; CPU device 0 otherwise)."""
    return Platform._get(TpuDevice, _accel_devices()[0])


def create_tpu_device_on(device_id: int) -> Device:
    return Platform.CreateTpuDeviceOn(device_id)


def create_replica_device(index: int = 0) -> Device:
    """A PRIVATE Device object for serving replica `index` — NOT the
    `Platform._cache` singleton `create_tpu_device()` returns. A
    Device owns single-writer dispatch state (its RNG key); a fleet
    runs one dispatcher thread per replica, so replicas sharing the
    cached Device object would race it (`singa_tpu.fleet` docs the
    failure mode). Replica `index` lands on accelerator
    `index % n_devices`, so an N-chip host spreads an N-replica fleet
    one-per-chip while a 1-chip (or CPU) host stacks them safely."""
    devs = _accel_devices()
    return TpuDevice(devs[int(index) % len(devs)])


def create_tpu_devices(num: int):
    return Platform.CreateTpuDevices(num)


def enable_lazy_alloc(flag: bool) -> None:
    """Parity shim: reference toggles cnmem lazy allocation; PJRT owns HBM."""


# ---------------------------------------------------------------------------
# Eager hot-path config. The reference configures execution policy on
# the device layer (EnableGraph, SetVerbosity); the TPU-native eager
# cache knobs live on the same surface. State is owned by
# `singa_tpu.stats` so autograd/opt read it without an import cycle.
# ---------------------------------------------------------------------------
def set_dag_cache_capacity(n: int) -> None:
    """Max entries in the recorded-backward executable cache
    (autograd._DAG_BWD_CACHE). Shrinking evicts immediately (negative
    entries first). Default 256; size it above the working set of
    distinct DAG shapes (e.g. the number of sequence-length buckets x
    models sharing the process)."""
    from . import stats

    stats.configure(dag_cache_capacity=n)


def set_dag_cache_policy(policy: str) -> None:
    """"lru" (default: hits promote, hot executables survive cycling
    workloads) or "fifo" (insertion order only — the pre-observability
    behavior, kept for A/B measurement; see
    benchmarks/eager_overhead.py)."""
    from . import stats

    stats.configure(dag_cache_policy=policy)


def set_buffer_donation(flag: bool) -> None:
    """Donate param/momentum/grad buffers into the jitted optimizer
    update and the graph-mode step (default on). Read at executable
    build time: an already-compiled graph-mode step keeps its donation
    contract until the model is re-compile()d."""
    from . import stats

    stats.configure(buffer_donation=flag)


def get_eager_config() -> dict:
    """Snapshot of the eager hot-path config knobs."""
    from . import stats

    return stats.get_config()


def set_bn_stats_dtype(dt) -> None:
    """BatchNorm statistics precision floor (byte-diet knob).

    None (default): batch mean/var and the normalization math run in
    at-least-fp32 — under bf16 AMP this materializes an fp32 copy of
    the activations that round-trips HBM (the reference-parity
    behavior). "bfloat16" / "float16": the floor drops, so bf16
    activations are normalized in bf16 and the fp32 round-trip
    disappears. Inputs are never DOWNcast: fp32 activations keep fp32
    statistics under any floor, and the f64 gradient-audit path is
    untouched. Read at op-dispatch / trace time — recompile graph-mode
    models (and the recorded-backward cache keys on it) after
    toggling."""
    from . import stats

    stats.configure(bn_stats_dtype=dt)


def set_inference_quant(mode: str) -> None:
    """Post-training quantization for the INFERENCE stack (ISSUE 19).

    "off" (default): fp32 decode/forward. "int8": decode-tier params
    become symmetric per-channel int8 with dequant-at-use and fp32
    accumulation, the serving KV slab becomes int8 payload + separate
    f32 scale planes, and forward executables stream int8 param
    payloads (singa_tpu.quant). Training paths ignore the knob;
    `generate()` stays fp32 — quant covers `decode_step`/`decode_scan`
    /`prefill_slab` and the ServingEngine forward path. Read at
    decode-program build time and part of
    `export_cache.knob_fingerprint()`: flipping it is an AOT-store
    miss, never a stale load. Serving engines size their slab at
    `warm_decode()` — arm the knob BEFORE building the engine."""
    from . import stats

    stats.configure(inference_quant=mode)


def set_step_guard(flag: bool) -> None:
    """Fold an all-finite check on loss + gradients into the compiled
    train step (default off). A non-finite step leaves params and
    optimizer slots bit-identical to their pre-step values via
    on-device selects — no host round-trip on the hot path — and
    increments the counters in `cache_stats()["resilience"]`. On a
    device mesh the finite bit is reduced over the global gradients
    inside the one SPMD program, so every rank skips identically.
    Read at executable build time: re-`compile()` an already-compiled
    graph-mode model after toggling (same contract as
    `set_buffer_donation`)."""
    from . import stats

    stats.configure(step_guard=flag)


def set_loss_scaling(init_scale=2.0 ** 15, growth_factor: float = 2.0,
                     backoff_factor: float = 0.5,
                     growth_interval: int = 2000,
                     min_scale: float = 1.0,
                     max_scale: float = 2.0 ** 24) -> None:
    """Dynamic loss scaling for the AMP path (implies the step guard).

    The backward seed is multiplied by a running scale; gradients are
    unscaled inside the fused/jitted update. After `growth_interval`
    consecutive finite steps the scale grows ×`growth_factor` (capped
    at `max_scale` — an uncapped scale overflows to inf under all-zero
    grads and backoff could never recover); an overflowed (non-finite)
    step skips the update and backs the scale off ×`backoff_factor`
    (floored at `min_scale`). Keep the factors powers of two and the
    scale/unscale round trip is bit-exact. `set_loss_scaling(None)`
    disables. Resets the live scale state; re-`compile()` graph-mode
    models after toggling."""
    from . import resilience, stats

    if init_scale is None:
        stats.configure(loss_scaling=None)
    else:
        stats.configure(loss_scaling={
            "init_scale": init_scale,
            "growth_factor": growth_factor,
            "backoff_factor": backoff_factor,
            "growth_interval": growth_interval,
            "min_scale": min_scale,
            "max_scale": max_scale,
        })
    resilience.reset_state()


def set_grad_accum(n: int) -> None:
    """Microbatched gradient accumulation factor (default 1 = off).

    With n > 1 the compiled train step reshapes its incoming batch to
    `[n, batch/n, ...]` and runs a `lax.scan` over the microbatches
    INSIDE the one XLA program — forward + backward per microbatch,
    gradients accumulated in fp32 — applying the optimizer exactly
    once on the mean at the end. Train at an effective batch n× what
    fits HBM (the live activation/gradient footprint stays at
    microbatch size), and on a device mesh the gradient reduction
    fires once per accumulated step instead of once per microbatch.
    The eager path microbatches the same way with one fused optimizer
    dispatch. The StepGuard finite check / DynamicLossScaler unscale
    run once on the ACCUMULATED gradients, and bf16 slot storage
    quantizes once at the final apply.

    Read at executable build time (same contract as
    `set_buffer_donation`/`set_step_guard`): re-`compile()` an
    already-compiled graph-mode model after toggling.
    `Model.compile(..., grad_accum=n)` overrides per-model. Batch
    sizes must divide by n (`singa_tpu.data.microbatches` is the
    feeding-side splitter). Geometry + applied-step counters surface
    in `cache_stats()["accum"]`."""
    from . import stats

    stats.configure(grad_accum=n)


def set_parallel_plan(plan=None, **axes) -> None:
    """Process-default `parallel.ParallelPlan` (ISSUE 10): the
    multi-axis geometry `Model.compile` adopts when called without
    `mesh`/`plan`. Pass a plan object, axis sizes
    (`set_parallel_plan(data=4, pipe=2)` builds one — extra keywords
    `pipeline_microbatches`/`pipeline_schedule`/`moe_capacity_factor`
    carry the policy), or nothing to clear. With a plan armed, a bare
    `compile(..., use_graph=True)` trains as one SPMD program over
    the plan's mesh: tensor-parallel layers under the GSPMD rules,
    `PipelineStack` stages on the "pipe" axis (1F1B schedule),
    `MoE` experts on the "expert" axis — composed with grad-accum,
    the step guard, and the loss scaler exactly like the DP path.
    Read at compile time: re-`compile()` after toggling (the
    `set_grad_accum` contract). Counters:
    `cache_stats()["parallel"]`."""
    from .parallel import plan as plan_mod

    if plan is not None and axes:
        raise ValueError(
            "set_parallel_plan: pass a ParallelPlan OR axis sizes, "
            "not both")
    if plan is None and axes:
        plan = plan_mod.ParallelPlan(**axes)
    plan_mod.set_process_plan(plan)


def set_remat_policy(policy, *names) -> None:
    """Scan-level rematerialization policy for the compiled train step
    (ISSUE 9; ROADMAP item 2's byte lever, searchable by the
    autotuner). None (default) = off; a named `jax.checkpoint` policy —
    "dots_saveable" (matmul/conv-free recompute: dot results stay
    saved, everything else is recomputed in the backward),
    "nothing_saveable" (maximum recompute: only region inputs
    survive), "dots_with_no_batch_dims_saveable",
    "everything_saveable" — or
    `set_remat_policy("save_anything_but_these_names", "a", "b")` for
    the name-keyed policy (pairs with `jax.ad_checkpoint.checkpoint_name`
    inside custom models).

    With a policy armed, the graph-mode step wraps each microbatch's
    ENTIRE forward+loss region in `jax.checkpoint(policy=...)` and
    derives its gradients from one `jax.vjp` over that region — inside
    `_JitStep._accum_step`'s `lax.scan` when gradient accumulation is
    on (fp32 accumulation preserved, the optimizer still applies once
    on the mean), and as a single whole-batch region when accumulation
    is off. Activation memory across the fwd→bwd boundary drops to the
    policy's saveable set; the recompute FLOPs are the price
    (μ-cuDNN's memory/recompute trade, arXiv:1804.04806). The effect
    is CPU-verifiable via `hlo_profile.peak_bytes_estimate` on
    `Model.step_hlo_text`. Composes with the per-op
    `autograd.set_remat` (which checkpoints individual op fns) and
    joins the export-cache knob fingerprint, so AOT artifacts can
    never go stale across a policy flip. Eager mode ignores the
    policy. Read at executable build time (the
    `set_buffer_donation`/`set_grad_accum` contract): re-`compile()`
    an already-compiled graph-mode model after toggling. Requires an
    optimizer on the model and `train_one_batch` to call
    `backward_and_update` exactly once (the grad-accum contract)."""
    from . import stats

    if names:
        policy = (policy, list(names))
    stats.configure(remat_policy=policy)


def set_tracing(flag: bool = True, ring_capacity: Optional[int] = None,
                profile_dir: Optional[str] = None,
                ship_capacity: Optional[int] = None) -> None:
    """Toggle the span-based host tracer (`singa_tpu.trace`).

    Disabled (the default) the tracer is a strict no-op — `span()`
    hands back a shared null context, nothing is recorded. Enabled,
    spans land in a bounded ring buffer: the step path is pre-wired
    (`BatchIter` data-wait, eager `train_one_batch` + fused optimizer
    apply, graph-step dispatch vs `block_until_ready` device-sync,
    sharded placement, resumable-loop checkpoint save/restore), so a
    training loop wrapped in `trace.step_span(i)` decomposes each
    step for `trace.export_chrome_trace(path)` (Perfetto-loadable),
    `trace.format_summary()`, and the `MetricsLogger` per-step JSONL.
    The serving/fleet request path is pre-wired too: every fleet
    request gets a trace context (`trace_id`) threaded through
    routing, failover, the IPC boundary, and the worker dispatch —
    `trace.merge_chrome_traces` folds N processes' spans into one
    aligned timeline (see README "Fleet observability").
    NOTE: enabling adds a device sync per graph-mode step (the
    device_sync span needs a fence to mean anything) — leave it off
    for peak-throughput runs. `ring_capacity` resizes the span ring
    (default 16384 spans); `profile_dir` is where
    `trace.profile_steps(n)` writes `jax.profiler` device traces;
    `ship_capacity` bounds the cross-process span ship-back buffer a
    fleet WORKER drains into reply/heartbeat frames (0 = off, the
    default — overflow drops oldest, counted `ship_dropped`).
    Counters: `cache_stats()["trace"]`."""
    from . import trace

    trace.configure(enabled=flag, ring_capacity=ring_capacity,
                    profile_dir=profile_dir,
                    ship_capacity=ship_capacity)


def set_export_cache(directory) -> None:
    """Arm the persistent AOT executable store (`singa_tpu.
    export_cache`): graph-mode train steps, sharded mesh steps, and
    forward executables are serialized with `jax.export` into
    `directory`, keyed by (model topology fingerprint, abstract shape
    signature, dtype, device kind, and a snapshot of every
    step-affecting knob), and a process that finds a matching artifact
    DESERIALIZES it instead of re-tracing — millisecond warm starts
    where tracing took seconds. A knob/topology change changes the
    key, so a stale artifact can never load; a corrupt artifact falls
    back to tracing loudly (`tools/export_cache_gc.py` lists/validates/
    collects the store). NOTE: export-cached steps run without buffer
    donation (see `_JitStep._build`). `None` disables. Counters:
    `cache_stats()["export"]`."""
    from . import export_cache

    export_cache.configure(directory=directory)


def set_shape_buckets(max_batch=None, seq_dim=None, max_seq=None) -> None:
    """Arm the powers-of-two shape-bucketing policy: forward/serving
    dispatches pad their batch dim (and `seq_dim`, when given — right
    padding, causal-attention-safe only) up to the next pow2 bucket
    and slice padded rows back off the outputs, so diverse traffic
    retraces at most once per bucket instead of once per novel shape
    — and fills at most that many export-cache artifacts. A shape
    above `max_batch`/`max_seq` raises `export_cache.
    BucketOverflowError` (loud, never a silent retrace). Ceilings
    must be powers of two. `set_shape_buckets()` with no args
    disables. Works with or without `set_export_cache`."""
    from . import export_cache

    if max_batch is None and max_seq is None and seq_dim is None:
        export_cache.configure(buckets=None)
    else:
        # seq_dim without max_seq falls through to BucketPolicy's own
        # "seq_dim set but max_seq missing" ValueError — silently
        # disabling a policy the caller thought they armed would leave
        # retraces unbounded with no signal.
        export_cache.configure(buckets=export_cache.BucketPolicy(
            max_batch=max_batch if max_batch is not None else 4096,
            seq_dim=seq_dim, max_seq=max_seq))


def set_serving(max_batch=None, max_wait_ms=None,
                max_queue=None) -> None:
    """Process defaults for the continuous-batching serving tier
    (`singa_tpu.serve.ServingEngine`): `max_batch` bounds the rows one
    fused dispatch coalesces, `max_wait_ms` is how long the dispatcher
    holds the FIRST queued request waiting for companions (the
    latency floor a lone request pays for batch occupancy), and
    `max_queue` bounds the admission queue (full ⇒ a loud
    `ServeQueueFullError` drop, counted in `cache_stats()["serve"]` —
    never an unbounded backlog). Engines constructed afterwards read
    these; per-engine constructor args override. Only the arguments
    given change."""
    from . import serve

    kw = {}
    if max_batch is not None:
        kw["max_batch"] = max_batch
    if max_wait_ms is not None:
        kw["max_wait_ms"] = max_wait_ms
    if max_queue is not None:
        kw["max_queue"] = max_queue
    if kw:
        serve.configure(**kw)


def set_serving_resilience(**kw) -> None:
    """Process defaults for the serving-tier resilience layer
    (`singa_tpu.serve.ServingEngine`; ISSUE 8). Only the keys given
    change; engines constructed afterwards read them (constructor
    args override per-engine). Keys:

      deadline_ms       default per-request deadline: still queued
                        past it ⇒ the future fails with
                        `ServeDeadlineError` BEFORE batch assembly
                        (counted `expired`); expired mid-dispatch ⇒
                        delivered but counted `late` with
                        `reply.deadline_exceeded=True`. None = off.
      max_retries       failed fused dispatches retry the whole group
                        this many times with exponential backoff
                        before bisecting to isolate poison requests.
      backoff_ms        base retry backoff (doubles per attempt).
      backoff_jitter    ± fraction of deterministic seed-keyed jitter.
      shed_watermark    queue depth at/above which NEW requests shed
                        with `ServeOverloadError` (carries
                        `retry_after_ms`). None = hard drop only.
      adaptive_wait     shrink the coalesce window toward 0 under
                        sustained queue depth (latency degrades
                        before availability).
      max_restarts      supervised dispatcher restarts before the
                        engine gives up and fails the queue.
      drain_timeout_s   `stop(drain=True)` bound: past it, remaining
                        futures fail with `ServeClosedError` instead
                        of the stop hanging on a dead dispatch.
      unhealthy_failures  consecutive dispatch-failure streak at
                        which `health()` turns unhealthy.
      health_file       JSON health-snapshot path probed by
                        `tools/serve_health.py` (exit code 0/1/2 =
                        ready/degraded/unhealthy). None = off.

    Counters: `cache_stats()["serve"]` (expired/late/shed/failed/
    poisoned/retries/dispatch_failures/restarts)."""
    from . import serve

    if kw:
        serve.configure_resilience(**kw)


def set_decode_serving(max_sessions=None, max_new_tokens=None,
                       prefill_batch=None, decode_block=None) -> None:
    """Process defaults for the KV-cached decode tier
    (`ServingEngine.submit_decode`; ISSUE 16): `max_sessions` sizes
    the KV-slot pool — the admission-control bound on concurrent
    generative sessions (queued + live; no free slot ⇒ a loud
    `ServeOverloadError` with `retry_after_ms`, counted `shed` in
    `cache_stats()["decode"]`); `max_new_tokens` caps the per-session
    generation length a submit may request; `prefill_batch` bounds how
    many new sessions prefill per dispatcher cycle (the prefill/decode
    split — long prompts never stall the fused decode batch by more
    than this); `decode_block` caps the greedy run-ahead — how many
    fused steps may dispatch as one scanned program when no session
    joins, leaves, expires, or samples inside the block (1 = every
    token its own dispatch). Engines constructed afterwards read
    these; per-engine constructor args override. Only the arguments
    given change."""
    from . import serve

    kw = {}
    if max_sessions is not None:
        kw["max_sessions"] = max_sessions
    if max_new_tokens is not None:
        kw["max_new_tokens"] = max_new_tokens
    if prefill_batch is not None:
        kw["prefill_batch"] = prefill_batch
    if decode_block is not None:
        kw["decode_block"] = decode_block
    if kw:
        serve.configure_decode(**kw)


def set_fleet(**kw) -> None:
    """Process defaults for the fleet serving tier
    (`singa_tpu.fleet.FleetRouter`; ISSUE 11). Only the keys given
    change; routers constructed afterwards read them (constructor
    args override per-router). Keys:

      max_failover_hops     re-submits of one request to DIFFERENT
                            replicas after a replica fails it
                            (`ServeDispatchError` / replica death).
                            Poison verdicts (`ServePoisonedError`)
                            never fail over. 0 = single-engine
                            semantics.
      max_shed_retries      rounds of honoring the smallest
                            `retry_after_ms` (seed-jittered) when
                            EVERY replica in rotation sheds; trying a
                            different replica costs no wait and
                            always comes first.
      max_shed_sleep_s      cap on one shed wait.
      health_max_age_s      health-snapshot age beyond which a
                            replica is ejected as stale (a wedged
                            writer stops refreshing; fail closed).
      probe_backoff_ms      base backoff between rejoin probes of an
                            ejected replica (doubles per failed
                            probe, seed-jittered).
      max_restarts          supervisor restarts per dead replica
                            before it is abandoned ("failed").
      supervise_interval_s  supervisor sweep period (restart/rejoin
                            latency floor).
      metrics_every         fleet metrics JSONL record every N routed
                            requests (transitions always log).

    Multi-process transport keys (ISSUE 13; `singa_tpu.fleet_proc`):

      transport             "engine" (in-process replicas), "proc"
                            (worker subprocesses behind the same
                            Replica protocol), or "tcp" (ISSUE 18:
                            listen-mode workers over a routable TCP
                            socket with generation fencing +
                            per-frame sequence numbers) — what
                            `fleet.make_replicas` builds.
      ipc_deadline_ms       per-message IPC bound: a missing admission
                            ACK (or a reply this far past the
                            request's own deadline) fails the caller
                            with a structured `ProcTransportError`
                            (`ServeDispatchError` subclass ⇒ the
                            router fails over unchanged).
      heartbeat_interval_s  worker heartbeat period; a missed
                            heartbeat ages the health snapshot into
                            the router's stale ejection (fail
                            closed). Keep `health_max_age_s` a few
                            multiples above it.
      spawn_timeout_s       bound on worker spawn → HELLO (shared by
                            the supervisor respawn path).
      max_inflight          in-flight requests per worker before the
                            parent sheds with `retry_after_ms`
                            instead of ballooning the pipe.

    TCP transport keys (ISSUE 18; modes listen/connect):

      reconnect_window_s    after a socket EOF/corruption in a TCP
                            mode, how long the parent holds the
                            worker's generation open for a
                            fence-checked reconnect before declaring
                            it dead (in-flight requests fail over
                            immediately; new submits shed with
                            `retry_after_ms` during the window).
      max_frame_bytes       reader-side bound on one frame's payload
                            (>= 1024): a hostile/corrupt length
                            prefix fails the connection with
                            `FrameCorruptError` instead of ballooning
                            RSS.

    Counters: `cache_stats()["fleet"]` (routed/failovers/refused/
    rejected, ejections/rejoins/restarts, per-replica state incl.
    transport ledgers)."""
    from . import fleet

    if kw:
        fleet.configure(**kw)


def set_slo(enabled: bool = True, **kw) -> None:
    """Arm (or disarm) the online SLO engine (`singa_tpu.slo`;
    ISSUE 20): mergeable streaming quantile sketches over the serving
    segments (queue_wait/ipc/dispatch/reply/ttft/tpot), multi-window
    burn-rate alerting over a declarative `SLOSpec`, and per-replica
    anomaly detection.  `set_slo(True, ...)` builds a FRESH engine —
    sketches, windows, and alert state start empty (documented reset
    semantics).  When disabled, every feed site is a strict no-op
    (zero allocation) and worker heartbeats carry no `slo` key at
    all.  Keys:

      rel_err            sketch relative-error bound (default 0.02):
                         any reported quantile is within this
                         relative distance of the true sample
                         quantile. Smaller = more buckets used.
      max_buckets        live-bucket budget per sketch (default 512);
                         overflow collapses the LOW tail upward,
                         counted loudly (`collapsed`), never the high
                         quantiles operators page on.
      window_scale       multiplies the canonical Google-SRE burn
                         windows (fast 1h/5m at burn 14.4 => page;
                         slow 3d/6h at burn 1.0 => ticket) down to
                         bench timescales. 1.0 = production windows.
      spec               {"availability": target,
                          "latency": {segment: {"threshold_ms": ...,
                                      "target": ...}}} — the SLO
                         itself. Latency objectives are request-based
                         (fraction of samples under the threshold).
      alerts_path        JSONL stream for alert state transitions
                         (schema-stable records; every transition of
                         pending -> firing -> resolved is one line).
      hb_gap_mult /      heartbeat-gap anomaly: breach when the gap
      hb_gap_min_s       exceeds max(min_s, mult * EWMA baseline).
      clock_mult /       clock anomaly: |offset_us| beyond the
      clock_slack_us     transport estimator's own uncertainty_us *
                         mult + slack.
      spike_window_s /   counter-rate anomaly: windowed counter delta
      spike_mult         vs max(per-counter floor, mult * EWMA).
      anomaly_pending_s/ holds before an anomaly fires / resolves
      anomaly_resolve_s  (flap suppression).

    Reads: `fleet.FleetRouter.slo_report()` (fleet-merged),
    `serve` health snapshots gain an `alerts` block, and
    `cache_stats()["slo"]` counts feeds/ingests/ticks/alerts."""
    from . import slo

    slo.configure(enabled, **kw)


def set_dag_auto_flops_per_op(v: float) -> None:
    """Recorded-backward auto-routing threshold (FLOPs/op): under
    `autograd.set_dag_backward("auto")` (the default), DAGs whose
    estimated mean backward FLOPs per op exceed this take the per-op
    walk (compute-bound: dispatch overhead is noise), the rest take
    the one-dispatch recorded replay. Routing decisions are surfaced
    in `cache_stats()['dag_route']`."""
    from . import stats

    stats.configure(dag_auto_flops_per_op=v)


# ---------------------------------------------------------------------------
# XLA flag profiles. XLA reads XLA_FLAGS at backend-client creation,
# so profiles must be applied before the first jax.devices() /
# computation of the process — bench.py's staged subprocesses apply
# them first thing (BENCH_XLA_PROFILE), which is the supported path.
# ---------------------------------------------------------------------------
_XLA_PROFILES = {
    # no-op baseline: whatever the environment already set
    "default": (),
    # The latency-hiding/fusion set used for bench runs (BASELINE.md
    # roofline: un-overlapped epilogues are part of the residual gap).
    # Scheduler overlaps collective/async work with compute; the
    # async-collective fusion flags let it move allgathers off the
    # critical path on meshed steps.
    "latency": (
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
        "--xla_tpu_overlap_compute_collective_tc=true",
    ),
}
_xla_profile_applied: Optional[str] = None


def set_xla_profile(name: str = "latency"):
    """Apply a named XLA flag profile by merging it into XLA_FLAGS.

    Returns the list of flags applied. Idempotent: re-applying a
    profile (or switching profiles) first strips every flag any
    profile here owns, so flags never duplicate or linger. Flags are
    consumed at backend init — if a jax backend already exists in this
    process, a warning is printed and the profile only affects
    backends created afterwards (bench.py stage subprocesses apply it
    before touching jax, which is the supported path)."""
    global _xla_profile_applied
    if name not in _XLA_PROFILES:
        raise ValueError(
            f"unknown XLA profile {name!r}; known: "
            f"{sorted(_XLA_PROFILES)}")
    owned = {f.split("=")[0] for flags in _XLA_PROFILES.values()
             for f in flags}
    current = [f for f in os.environ.get("XLA_FLAGS", "").split()
               if f.split("=")[0] not in owned]
    flags = list(_XLA_PROFILES[name])
    os.environ["XLA_FLAGS"] = " ".join(current + flags).strip()
    _xla_profile_applied = name
    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            import sys

            print("singa_tpu: set_xla_profile applied after backend "
                  "init; flags only affect backends created later",
                  file=sys.stderr)
    except Exception:
        pass
    return flags


def get_xla_profile() -> Optional[str]:
    """Name of the profile applied by set_xla_profile (None if never
    called in this process)."""
    return _xla_profile_applied


# ---------------------------------------------------------------------------
# Migration aliases: the reference's Python API spells these
# `device.create_cuda_gpu*` (python/singa/device.py). Keep the names so
# reference user code ports by import-swap; they build TPU devices here.
# ---------------------------------------------------------------------------
create_cuda_gpu = create_tpu_device
create_cuda_gpu_on = create_tpu_device_on
create_cuda_gpus = create_tpu_devices
