"""Evaluation metrics.

Reference parity: `python/singa/metric.py` — `Metric` base with
`forward/evaluate`, `Accuracy` (top-k), `Precision`, `Recall`
(SURVEY.md §2.2 P9). Computation happens on-device via jnp and reduces
to a host scalar only at `evaluate`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


class Metric:
    """Reference: `metric.Metric`."""

    def forward(self, x, y):
        """Per-sample metric values (device array)."""
        raise NotImplementedError

    def evaluate(self, x, y) -> float:
        """Batch-averaged metric as a host float."""
        return float(jnp.mean(self.forward(x, y)))

    def __call__(self, x, y) -> float:
        return self.evaluate(x, y)

    def register(self, logger, name=None):
        """Register this metric into a `trace.MetricsLogger`: every
        `log_step(..., outputs=..., labels=...)` evaluates it and the
        value lands under `record["metrics"][name]` — eval metrics in
        the same JSONL stream as the loss (ISSUE 5). `name` defaults
        to the lowercased class name. Returns self (chainable):

            with trace.MetricsLogger(path) as ml:
                metric.Accuracy().register(ml, "top1")
        """
        logger.register_metric(name or type(self).__name__.lower(),
                               self)
        return self


class Accuracy(Metric):
    """Reference: `metric.Accuracy(top_k)` — fraction of samples whose
    true label is within the top-k predictions."""

    def __init__(self, top_k: int = 1):
        self.top_k = int(top_k)

    def forward(self, x, y):
        logits, labels = _arr(x), _arr(y)
        if labels.ndim == logits.ndim:  # one-hot → index
            labels = jnp.argmax(labels, axis=-1)
        if self.top_k == 1:
            return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        _, topk = jax.lax.top_k(logits, self.top_k)
        return jnp.any(topk == labels[..., None], axis=-1).astype(jnp.float32)


class Precision(Metric):
    """Binary precision at threshold 0.5 over probabilities/logits>0."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def evaluate(self, x, y) -> float:
        pred = np.asarray(_arr(x)) > self.threshold
        true = np.asarray(_arr(y)) > 0.5
        tp = np.logical_and(pred, true).sum()
        return float(tp / np.maximum(pred.sum(), 1))


class Recall(Metric):
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def evaluate(self, x, y) -> float:
        pred = np.asarray(_arr(x)) > self.threshold
        true = np.asarray(_arr(y)) > 0.5
        tp = np.logical_and(pred, true).sum()
        return float(tp / np.maximum(true.sum(), 1))
