"""Model: the user-facing training class.

Reference parity: `python/singa/model.py` — `Model(Layer)` with
`compile(inputs, is_train, use_graph, sequential)`, user-overridden
`forward` and `train_one_batch`, `train()/eval()` flags,
`save_states/load_states` (zip of npz + aux meta), `set_optimizer`.

TPU-native graph mode: the reference's `compile(use_graph=True)` runs
one traced forward/backward with `Device::EnableGraph(true)`, then
replays `Graph::Run()` each step (SURVEY.md §1). Here the same
user-level contract lowers to ONE `jax.jit`-compiled XLA program per
step: `compile` traces `train_one_batch` with params / layer states /
optimizer state / RNG key bound to jit tracers, captures their updated
values as program outputs, and replays the compiled executable each
call with buffer donation (XLA aliases param memory — the reference's
in-place Block mutation, done the immutable way).

Eager mode (`use_graph=False`) runs the identical Python code per-op —
the graph-vs-eager loss parity test is the key invariant kept from the
reference (`test/python/test_model.py`).
"""
from __future__ import annotations

import io
import json
import os
import time
import zipfile
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional

import jax
import numpy as np

from . import autograd, stats as stats_mod, tensor as tensor_mod, \
    trace as trace_mod
from .layer import Layer
from .tensor import Tensor


class Model(Layer):
    """Reference: `model.Model`."""

    def __init__(self, name=None):
        super().__init__(name)
        self._optimizer = None
        self._jit_step = None
        self._jit_fwd = None
        self._use_graph = False
        self._mesh = self._rules = self._batch_specs = None
        self._plan = None
        # Per-model gradient-accumulation override (None = defer to
        # the process knob, device.set_grad_accum / stats config).
        self._grad_accum = None
        self.training = True

    # -- configuration -----------------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer

    @property
    def optimizer(self):
        return self._optimizer

    def compile(self, inputs: List[Tensor], is_train: bool = True,
                use_graph: bool = False, sequential: bool = False,
                mesh=None, rules=None, batch_specs=None,
                grad_accum=None, plan=None):
        """Reference: `Model.compile` — one tracing pass to initialize
        params (lazy shape inference), then optionally arm graph mode.

        `sequential` is accepted for API parity (the reference uses it
        to serialize graph exec; XLA owns scheduling here).

        Mesh mode (TPU-native, no reference equivalent): passing a
        `jax.sharding.Mesh` turns the compiled step into one SPMD
        program over the mesh — params laid out by `rules`
        (`parallel.ShardingRules`), batch dims sharded over the "data"
        axis (`batch_specs` overrides per-input), gradients reduced by
        XLA over ICI. This subsumes DistOpt: same math, one program.

        `grad_accum=n` arms microbatched gradient accumulation for
        this model (overriding the process knob
        `device.set_grad_accum`): the train step splits its batch into
        n microbatches, scans forward/backward over them inside the
        compiled program (eager mode loops the same microbatches with
        one fused optimizer dispatch), accumulates gradients in fp32,
        and applies the optimizer once on the mean. Batch sizes must
        divide by n. `grad_accum=1` pins accumulation OFF regardless
        of the process knob; None defers to it.

        `plan` (a `parallel.ParallelPlan`, ISSUE 10) is the multi-axis
        spelling of mesh mode: it names the mesh geometry
        (dp x model x pipe x expert x seq), the sharding rules, and
        the pipeline/MoE policy in one object. compile builds the
        mesh from it, wires it into every mesh-aware layer
        (`PipelineStack`, `MoE`, `MultiHeadAttention` — anything with
        a `mesh` attribute left at None), and keys the AOT export
        cache on `plan.fingerprint()`. When neither `plan` nor `mesh`
        is given, the process default (`device.set_parallel_plan`)
        applies.
        """
        if plan is None and mesh is None:
            from .parallel import plan as plan_mod

            plan = plan_mod.process_plan()
        if plan is not None:
            if mesh is not None:
                raise ValueError(
                    "compile: pass either plan= or mesh=, not both "
                    "(the plan builds its own mesh)")
            mesh = plan.build_mesh()
            if rules is None:
                rules = plan.build_rules()
            # wire the mesh + plan policy into every mesh-aware layer;
            # a RE-compile with a different plan re-wires everything
            # the previous plan set (layers track which attrs the
            # user pinned vs the plan filled)
            stack = [self]
            while stack:
                l = stack.pop()
                if l is not self:
                    if hasattr(l, "_apply_plan"):
                        l._apply_plan(plan, mesh)
                    elif hasattr(l, "mesh") and (
                            l.mesh is None
                            or getattr(l, "_mesh_from_plan", False)):
                        l.mesh = mesh
                        l._mesh_from_plan = True
                stack.extend(l.sublayers.values())
        self._plan = plan
        if grad_accum is not None:
            grad_accum = int(grad_accum)
            if grad_accum < 1:
                raise ValueError(
                    f"grad_accum must be >= 1, got {grad_accum}")
        self._grad_accum = grad_accum
        self.train(is_train)
        dev = inputs[0].device if inputs else None
        if dev is not None:
            dev.EnableGraph(use_graph)
        # One forward initializes all lazy params.  The default path
        # runs it under `jax.eval_shape` at batch 1: network ops trace
        # abstractly (zero XLA compilation), while param fills compute
        # host-side numpy values from the concrete RNG key — so
        # ResNet-50 compile is ~2 s where the round-4 jitted-init
        # design paid a 17 s XLA backend compile of the init program.
        # Falls back to the eager per-op init if the trace fails or a
        # custom initialize() depends on concrete input values.
        if inputs and not self.param_tensors():
            # Initialization runs in EVAL mode: param creation must not
            # depend on input values or advance training state (BN
            # running stats stay at their init values, no dropout keys
            # are consumed).  The reference's compile pass runs with
            # placeholder data, so its BN stats absorb garbage; here
            # compile is a pure shape+RNG pass — which is also what
            # lets `_eval_shape_init_forward` skip XLA entirely.
            self.train(False)
            try:
                if not self._eval_shape_init_forward(inputs, dev):
                    self._host_init_forward(inputs, dev)
            finally:
                self.train(is_train)
        elif inputs:
            # Params already exist (a forward ran before compile):
            # run the tracing forward in place.
            self.forward(*inputs)
        self._use_graph = use_graph or mesh is not None
        self._mesh, self._rules, self._batch_specs = mesh, rules, batch_specs
        self._jit_step = None  # (re)built lazily on first train_one_batch
        self._jit_fwd = None
        if dev is not None:
            dev.EnableGraph(False)

    def _eval_shape_init_forward(self, inputs, dev) -> bool:
        """Run the lazy-param-init forward under `jax.eval_shape` —
        the zero-compile init path (VERDICT r4 next #6).

        The network ops trace abstractly (no XLA compilation, no
        execution — the 17+ s backend compile of the batch-1 init
        program for ResNet-50 disappears), while the `initialize`
        hooks draw from the CONCRETE host RNG key, so param values
        are computed eagerly as tiny per-shape programs and match the
        eager init path bit-for-bit.  Requires init to be
        value-independent, which eval-mode init guarantees for the
        in-tree layers; models whose eval forward rebinds state from
        input-dependent values leak a tracer into a param/state — we
        detect that and fall back (returning False leaves the model
        untouched)."""
        from .device import get_default_device

        cpu = get_default_device()
        full = os.environ.get("SINGA_TPU_INIT_FULL_BATCH", "0") == "1"
        specs = []
        for t in inputs:
            shape = tuple(t.shape)
            if not full and len(shape) >= 1 and shape[0] > 1:
                shape = (1,) + shape[1:]
            specs.append(jax.ShapeDtypeStruct(shape, t.dtype))
        borrow = dev is not None and dev is not cpu
        saved_cpu_key = cpu._rng_key
        if borrow:
            cpu._rng_key = jax.device_put(np.asarray(dev._rng_key),
                                          cpu.jax_device)
        snap = _lazy_snapshot(self)

        def init_fn(*batch):
            xs = [tensor_mod.from_raw(b, cpu) for b in batch]
            self.forward(*xs)
            return 0

        def _undo():
            _lazy_restore(self, snap)
            cpu._rng_key = saved_cpu_key

        try:
            jax.eval_shape(init_fn, *specs)
        except Exception as e:
            import sys

            print(f"singa_tpu: eval_shape init failed "
                  f"({type(e).__name__}: {e}); falling back",
                  file=sys.stderr)
            _undo()
            return False
        leaked = [t for t in self.param_tensors() + self.state_tensors()
                  if isinstance(t.data, jax.core.Tracer)]
        if leaked:
            import sys

            print("singa_tpu: eval_shape init leaked tracers into "
                  f"{len(leaked)} tensors (value-dependent init); "
                  "falling back", file=sys.stderr)
            _undo()
            return False
        if borrow:
            dev._rng_key = jax.device_put(np.asarray(cpu._rng_key),
                                          dev.jax_device)
            cpu._rng_key = saved_cpu_key
        if dev is not None and dev is not cpu:
            for t in self.param_tensors() + self.state_tensors():
                t.to_device(dev)
        return True

    def _host_init_forward(self, inputs, dev):
        """Run the param-init forward on host CPU, borrowing `dev`'s RNG
        stream so `dev.SetRandSeed(...)` still governs init values, then
        move every created param/state onto `dev`.

        Multi-controller inputs (global arrays spanning processes) are
        replaced by their local shard for this pass — lazy init only
        reads feature dims, which batch shardings leave whole.

        Uses the same batch-1 slicing policy as
        `_eval_shape_init_forward`, and compile() wraps both paths in
        eval mode, so
        the two init paths leave identical model state (params by RNG
        determinism; BN running stats stay at creation values — eval
        mode never updates them).
        """
        from .device import get_default_device

        cpu = get_default_device()
        full = os.environ.get("SINGA_TPU_INIT_FULL_BATCH", "0") == "1"
        borrow = dev is not None and dev is not cpu
        if borrow:
            saved_cpu_key = cpu._rng_key
            cpu._rng_key = jax.device_put(dev._rng_key, cpu.jax_device)
        try:
            host_inputs = []
            for t in inputs:
                arr = t.data
                if not getattr(arr, "is_fully_addressable", True):
                    arr = arr.addressable_shards[0].data
                arr = np.asarray(arr)
                if not full and arr.ndim >= 1 and arr.shape[0] > 1:
                    arr = arr[:1]
                h = t.clone()
                h.data = jax.device_put(arr, cpu.jax_device)
                h.device = cpu
                host_inputs.append(h)
            self.forward(*host_inputs)
        finally:
            if borrow:
                dev._rng_key = jax.device_put(cpu._rng_key, dev.jax_device)
                cpu._rng_key = saved_cpu_key
        if dev is not None and dev is not cpu:
            for t in self.param_tensors() + self.state_tensors():
                t.to_device(dev)

    def train(self, mode: bool = True):
        self.training = mode
        autograd.training = mode

    def eval(self):
        self.train(False)

    # -- user-overridable --------------------------------------------------
    def forward(self, *xs):
        raise NotImplementedError

    def loss(self, out, ty):
        """Default loss hook; user models commonly override
        train_one_batch wholesale (reference examples do)."""
        return autograd.softmax_cross_entropy(out, ty)

    def optim(self, loss):
        return self._optimizer.backward_and_update(loss)

    def train_one_batch(self, x: Tensor, y: Tensor):
        if self._optimizer is None:
            raise RuntimeError(
                "train_one_batch requires an optimizer: call "
                "model.set_optimizer(...) before training"
            )
        out = self.forward(x)
        l = self.loss(out, y)
        self.optim(l)
        # Step accounting for cache observability: retraces/step after
        # warmup is the retrace-storm signal (stats.cache_stats()).
        # Counted here (not in __call__) so user models overriding
        # train_one_batch wholesale — the reference's idiom — opt out
        # explicitly rather than silently, and the graph path counts
        # in _JitStep.__call__ where a trace is one step too.
        stats_mod.count_train_step()
        return out, l

    def __call__(self, *args, **kwargs):
        """Reference: `Model.__call__` routes to `train_one_batch` in
        train mode (graph replay when compiled with use_graph) and to
        `forward` in eval mode."""
        if self.training and (self._optimizer is not None or len(args) > 1):
            return self.train_one_batch_dispatch(*args, **kwargs)
        if self._use_graph and not kwargs:
            return self.forward_graph(*args)
        return self.forward(*args, **kwargs)

    # -- graph (jit) execution --------------------------------------------
    def train_one_batch_graph(self, *batch: Tensor):
        """Run `train_one_batch` as one compiled XLA program.

        Called automatically by `train_one_batch_dispatch`; also public
        for direct use. First call traces+compiles; subsequent calls
        replay with donated buffers.
        """
        if self._jit_step is None:
            if getattr(self, "_mesh", None) is not None:
                from .parallel.trainer import ShardedJitStep

                self._jit_step = ShardedJitStep(
                    self, self._mesh, rules=self._rules,
                    batch_specs=self._batch_specs,
                    plan=getattr(self, "_plan", None))
            else:
                self._jit_step = _JitStep(self)
        return self._jit_step(*batch)

    def train_one_batch_dispatch(self, *batch: Tensor):
        if self._use_graph:
            return self.train_one_batch_graph(*batch)
        n = self._accum_n()
        # Spanned HERE (not in train_one_batch) so user models that
        # override train_one_batch wholesale — the reference idiom —
        # still get the eager step on the timeline; the graph path
        # gets its dispatch/device_sync spans in _JitStep instead.
        with trace_mod.span("train_one_batch"):
            if n > 1 and self._optimizer is not None:
                return self._train_one_batch_accum_eager(n, *batch)
            return self.train_one_batch(*batch)

    def _accum_n(self) -> int:
        """Effective gradient-accumulation factor: the per-model
        `compile(grad_accum=...)` override, else the process knob
        (`device.set_grad_accum`)."""
        if self._grad_accum is not None:
            return self._grad_accum
        return stats_mod.grad_accum_n()

    def _train_one_batch_accum_eager(self, n: int, *batch: Tensor):
        """Eager-mode gradient accumulation: split the batch into n
        microbatches (`data.microbatches`), run the user's
        `train_one_batch` per microbatch with the optimizer in capture
        mode (backward runs — scaled seed included — but the apply is
        deferred), accumulate gradients in fp32 with a jitted adder,
        and apply the optimizer ONCE on the mean via
        `opt.apply_accumulated` — so an n-accum eager step pays one
        fused optimizer dispatch instead of n, and the StepGuard /
        DynamicLossScaler / bf16-slot policies all act once on the
        accumulated gradients, exactly like the scan-fused graph step.

        Returns the same pytree shape `train_one_batch` returns:
        batch-dim outputs are the microbatch outputs concatenated
        back to the full batch; scalar (loss) leaves become the mean
        over microbatches."""
        import jax.numpy as jnp

        from . import data as data_mod

        opt = self._optimizer
        micro = data_mod.microbatches(list(batch), n)
        order = None
        acc = loss_sum = None
        outs = []
        for mb in micro:
            opt._accum_begin()
            try:
                out = self.train_one_batch(*mb)
            finally:
                cap = opt._accum_end()
            if len(cap) != 1:
                raise RuntimeError(
                    "gradient accumulation requires train_one_batch "
                    "to call backward_and_update exactly once per "
                    f"microbatch; it ran {len(cap)} times")
            loss_t, pairs = cap[0]
            gs = [g.data if isinstance(g, Tensor) else g
                  for _, g in pairs]
            loss_arr = (loss_t.data if isinstance(loss_t, Tensor)
                        else jnp.asarray(loss_t))
            if order is None:
                order = [p for p, _ in pairs]
                acc, loss_sum = _accum_seed(gs, loss_arr)
            else:
                if [id(p) for p, _ in pairs] != [id(p) for p in order]:
                    raise RuntimeError(
                        "gradient accumulation: the (param, grad) "
                        "pair order changed across microbatches — "
                        "train_one_batch must be structurally "
                        "identical per microbatch")
                acc, loss_sum = _accum_add(acc, gs, loss_sum, loss_arr)
            outs.append(_unwrap_out(out))
        mb_size = micro[0][0].data.shape[0] if hasattr(
            micro[0][0], "data") else len(micro[0][0])
        stats_mod.note_accum_build(n, mb_size, mb_size * n)
        opt.apply_accumulated(loss_sum, list(zip(order, acc)), n)
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *outs)
        merged = _merge_accum_out(stacked, mb_size)
        dev = batch[0].device if batch and isinstance(
            batch[0], Tensor) else None
        return jax.tree_util.tree_map(
            lambda a: tensor_mod.from_raw(a, dev), merged)

    def _class_source_digest(self, h) -> None:
        """Fold this model's class identity + source into hasher `h` —
        the shared prelude of every topology fingerprint (a forward()
        edit must orphan cached AOT artifacts)."""
        import inspect

        h.update(type(self).__qualname__.encode())
        try:
            h.update(inspect.getsource(type(self)).encode())
        except (OSError, TypeError):
            pass  # source unavailable (REPL/frozen): inventory only

    # Layer machinery + per-run mutables that must NOT key an AOT
    # artifact: tensors/sublayers are inventoried separately, and
    # train/eval flags ride the export key's own extras.
    _FP_SKIP_ATTRS = frozenset({
        "_params", "_sublayers", "_state_attrs", "_initialized",
        "training", "_use_graph", "_jit_step", "_jit_fwd",
        "_optimizer", "_mesh", "_rules", "_batch_specs", "_plan",
    })

    def topology_fingerprint(self) -> str:
        """Stable identity of this model's traced program structure:
        class + source + the full param/state inventory (names,
        shapes, dtypes) + every layer's scalar CONFIG attributes —
        two instances with identical weights but e.g. `causal=True`
        vs `False`, or a stride that leaves kernel shapes unchanged,
        trace different programs and must never share an artifact.
        Keys the `export_cache` artifact store; models whose program
        is data-driven rather than source-driven override this
        (`sonnx.SONNXModel` hashes the imported ONNX graph)."""
        import hashlib
        import json

        h = hashlib.sha256()
        self._class_source_digest(h)
        for name, t in sorted(self.get_params().items()) + sorted(
                self.get_states().items()):
            h.update(f"{name}:{tuple(t.shape)}:{t.dtype}".encode())

        def config_of(layer):
            out = {}
            for k, v in layer.__dict__.items():
                if k in Model._FP_SKIP_ATTRS:
                    continue
                if isinstance(v, (bool, int, float, str, type(None))):
                    out[k] = v
                elif isinstance(v, (tuple, list)) and all(
                        isinstance(x, (bool, int, float, str,
                                       type(None))) for x in v):
                    out[k] = list(v)
            return out

        stack = [("", self)]
        while stack:
            path, l = stack.pop()
            h.update(json.dumps([path, config_of(l)],
                                sort_keys=True).encode())
            for k in sorted(l.sublayers):
                stack.append((f"{path}/{k}", l.sublayers[k]))
        return h.hexdigest()

    def cache_stats(self):
        """Snapshot of every executable-cache's counters
        (`singa_tpu.stats.cache_stats()`): the DAG backward cache, the
        per-op executable cache, and the fused-optimizer cache, plus
        the global train-step count. The numbers are process-global
        (caches are shared across models by design — two models with
        identical DAG structure share executables)."""
        return stats_mod.cache_stats()

    def step_hlo_text(self, *batch, optimized: bool = True) -> str:
        """HLO of the whole-step jit program for `batch` (never
        executed — model/optimizer arrays are untouched apart from
        `_ensure_opt_slots` pre-creating missing slot zeros). The
        input to `hlo_profile.bytes_accessed`/`profile_hlo`: how tests
        and tools measure a byte-diet knob's effect without a chip.
        `optimized=False` returns the pre-optimization HLO instead
        (no XLA compile paid) — the view where the remat policy's
        checkpoint barriers survive, which is what
        `hlo_profile.peak_bytes_estimate` meters (see
        `_JitStep.lowered_text`). Reuses (or primes) the model's own
        `_jit_step` executable, so inspecting a training model — or
        inspecting then training — pays the whole-step XLA compile
        once, not twice."""
        if self._jit_step is None:
            if getattr(self, "_mesh", None) is not None:
                from .parallel.trainer import ShardedJitStep

                self._jit_step = ShardedJitStep(
                    self, self._mesh, rules=self._rules,
                    batch_specs=self._batch_specs,
                    plan=getattr(self, "_plan", None))
            else:
                self._jit_step = _JitStep(self)
        return self._jit_step.lowered_text(*batch, optimized=optimized)

    def _ensure_forward_exec(self) -> "_JitForward":
        """The model's forward-executable wrapper, created lazily —
        shared by `forward_graph`, the serving engine (`serve.py`
        dispatches through it so requests hit the same warm AOT
        artifacts), and the prewarm tool's dry-run key probe."""
        if self._jit_fwd is None:
            self._jit_fwd = _JitForward(self)
        return self._jit_fwd

    def forward_graph(self, *xs: Tensor):
        """Run `forward` as one compiled XLA program (the eval-path
        analogue of `train_one_batch_graph`; reference eval replays the
        same buffered Graph)."""
        return self._ensure_forward_exec()(*xs)

    # -- checkpoint --------------------------------------------------------
    def state_snapshot(self, aux_states: Optional[Dict] = None):
        """Capture a consistent (states, meta) snapshot of the model +
        optimizer. The returned arrays are the CURRENT device buffers
        by reference. NOTE: a graph-mode train step DONATES these
        buffers to XLA (`_JitStep`, donate_argnums) — deferred readers
        must fork them first (`checkpoint.AsyncCheckpointer` makes
        device-side copies); immediate serialization (`save_states`)
        is safe as-is."""
        model_states = self.get_states()
        states = {k: v.data for k, v in model_states.items()}
        opt_meta = {}
        if self._optimizer is not None:
            opt_meta["step_counter"] = int(self._optimizer.step_counter)
            # Optimizer slots are keyed by id(param) in-memory; persist
            # them by param NAME so they survive into a fresh process.
            name_of = {id(t): n for n, t in model_states.items()}
            for pid, slots in self._optimizer.states.items():
                pname = name_of.get(pid)
                if pname is None:
                    continue
                for slot, arr in slots.items():
                    states[f"__opt__/{pname}/{slot}"] = arr
        from . import resilience

        if resilience.guard_active():
            # scale/backoff history resumes with the weights — a
            # restart must not restart the loss scale from init
            opt_meta["resilience"] = resilience.export_host_state()
        meta = {"aux": _jsonable(aux_states or {}), "opt": opt_meta,
                "names": list(states.keys())}
        return states, meta

    @staticmethod
    def write_states_zip(fpath: str, states: Dict, meta: Dict):
        """Serialize a `state_snapshot` to the checkpoint zip format
        (device→host transfer happens here, per array)."""
        with zipfile.ZipFile(fpath, "w") as zf:
            for name, arr in states.items():
                buf = io.BytesIO()
                arr = np.asarray(arr)
                if arr.dtype.name == "bfloat16":
                    # np.save round-trips ml_dtypes bf16 as raw V2
                    # void (dtype lost); store the exact values as
                    # fp32 (bf16 ⊂ fp32) — the slot_dtype policy
                    # re-quantizes on the first post-restore update.
                    arr = arr.astype(np.float32)
                np.save(buf, arr)
                zf.writestr(name.replace("/", "__SLASH__") + ".npy",
                            buf.getvalue())
            zf.writestr("__meta__.json", json.dumps(meta))

    def save_states(self, fpath: str, aux_states: Optional[Dict] = None):
        """Reference: `Model.save_states` — zipfile of per-tensor npz
        plus a json meta blob with aux states. Synchronous; see
        `singa_tpu.checkpoint.AsyncCheckpointer` for the non-blocking
        variant."""
        states, meta = self.state_snapshot(aux_states)
        self.write_states_zip(fpath, states, meta)

    def load_states(self, fpath: str) -> Dict:
        """Reference: `Model.load_states`. Returns aux states dict."""
        with zipfile.ZipFile(fpath, "r") as zf:
            meta = json.loads(zf.read("__meta__.json"))
            arrays = {}
            for name in meta["names"]:
                raw = zf.read(name.replace("/", "__SLASH__") + ".npy")
                arrays[name] = np.load(io.BytesIO(raw))
        model_states = {k: v for k, v in arrays.items()
                        if not k.startswith("__opt__/")}
        self.set_states(model_states)
        if self._optimizer is not None and meta.get("opt"):
            import jax.numpy as jnp

            self._optimizer.step_counter = meta["opt"].get("step_counter", 0)
            tensor_of = self.get_states()
            for key, arr in arrays.items():
                if not key.startswith("__opt__/"):
                    continue
                _, pname, slot = key.split("/", 2)
                t = tensor_of.get(pname)
                if t is not None:
                    self._optimizer.states.setdefault(id(t), {})[slot] = jnp.asarray(arr)
        if meta.get("opt", {}).get("resilience"):
            from . import resilience

            resilience.import_host_state(meta["opt"]["resilience"])
        self._jit_step = None  # state changed: force retrace
        self._jit_fwd = None
        return meta.get("aux", {})

    def fit_resumable(self, manager, batch_fn, total_steps: int,
                      save_every: int = 10, metrics=None):
        """Crash-consistent training loop: restore the latest VALID
        checkpoint from `manager` (a `checkpoint.CheckpointManager` —
        corrupt/truncated newest checkpoints are skipped via their
        content-digest manifests), then train to `total_steps`,
        checkpointing every `save_every` steps. `batch_fn(step)` must
        deterministically produce that step's (x, y) batch so a
        resumed run's loss trajectory matches the uninterrupted one.
        `metrics` (a `trace.MetricsLogger`) logs one structured JSONL
        record per executed step. Returns {step: loss} for the steps
        this call ran. See `singa_tpu.resilience.run_resumable`."""
        from . import resilience

        return resilience.run_resumable(self, manager, batch_fn,
                                        total_steps,
                                        save_every=save_every,
                                        metrics=metrics)


def _lazy_snapshot(root: Layer):
    """Record every layer's lazy-init state (for rollback if a traced
    init forward fails midway, leaving tracer-valued params behind)."""
    recs = []
    stack = [root]
    while stack:
        l = stack.pop()
        recs.append((l, l._initialized,
                     OrderedDict(l.__dict__.get("_params", ())),
                     list(l.__dict__.get("_state_attrs", ())),
                     set(l.sublayers.keys())))
        stack.extend(l.sublayers.values())
    return recs


def _lazy_restore(root: Layer, recs):
    for l, inited, params, state_attrs, subkeys in recs:
        l._initialized = inited
        l.__dict__["_params"] = OrderedDict(params)
        l.__dict__["_state_attrs"] = list(state_attrs)
        subs = l.__dict__.get("_sublayers")
        if subs is not None:
            for k in [k for k in subs if k not in subkeys]:
                del subs[k]


def _jsonable(d):
    out = {}
    for k, v in d.items():
        if isinstance(v, (int, float, str, bool, list, dict, type(None))):
            out[k] = v
        else:
            out[k] = float(v) if np.isscalar(v) else np.asarray(v).tolist()
    return out


@contextmanager
def _bound_model(params, states, dev, pvals, svals, key):
    """Bind tracer/program values onto the live param/state tensors and
    the device RNG key for the duration of a traced call, restoring the
    concrete arrays afterwards. The shared functionalization core of
    `_JitStep` and `_JitForward`."""
    saved_p = [p.data for p in params]
    saved_s = [s.data for s in states]
    saved_key = dev._rng_key
    try:
        for p, v in zip(params, pvals):
            p.data = v
        for s, v in zip(states, svals):
            s.data = v
        dev._rng_key = key
        yield
    finally:
        for p, v in zip(params, saved_p):
            p.data = v
        for s, v in zip(states, saved_s):
            s.data = v
        dev._rng_key = saved_key


def _unwrap_out(out):
    return jax.tree_util.tree_map(
        lambda t: t.data if isinstance(t, Tensor) else t,
        out,
        is_leaf=lambda t: isinstance(t, Tensor),
    )


# ---------------------------------------------------------------------------
# Gradient-accumulation helpers (ISSUE 4). The fp32 accumulator math is
# deliberately identical between the eager loop (jitted seed/add below)
# and the scan-fused graph step (same expressions traced into the scan
# body), so the two modes accumulate bit-identically: the sum order is
# sequential-by-microbatch in both, and the final mean is an
# elementwise division (never reassociated by fusion).
# ---------------------------------------------------------------------------
def _accum_seed_fn(gs, loss):
    import jax.numpy as jnp

    return ([g.astype(jnp.float32) for g in gs],
            jnp.mean(jnp.asarray(loss)).astype(jnp.float32))


def _accum_add_fn(acc, gs, loss_sum, loss):
    import jax.numpy as jnp

    return ([a + g.astype(jnp.float32) for a, g in zip(acc, gs)],
            loss_sum + jnp.mean(jnp.asarray(loss)).astype(jnp.float32))


# One jitted executable each, cached by jax per grad-list structure;
# the running accumulator and loss sum are donated so XLA adds in
# place instead of round-tripping fresh buffers every microbatch.
_accum_seed = jax.jit(_accum_seed_fn)
_accum_add = jax.jit(_accum_add_fn, donate_argnums=(0, 2))


def _merge_accum_out(stacked, mb: int):
    """Collapse per-microbatch outputs stacked on a leading [n] axis
    back to the monolithic step's output shape: leaves carrying the
    microbatch dim are concatenated to the full batch ([n, mb, ...] →
    [n*mb, ...]), inexact leaves without it (the loss scalar) become
    the mean over microbatches, and anything else (integer metadata)
    keeps the last microbatch's value.

    Known limitation: batch-ness is inferred by SHAPE (leading dim ==
    microbatch size). A non-batch output vector whose length happens
    to equal the microbatch size is indistinguishable from a
    per-sample output and gets concatenated rather than averaged —
    pick a microbatch size that differs from such output dims (this
    is inherent to shape-based inference; train_one_batch outputs
    carry no axis annotations)."""
    import jax.numpy as jnp

    def leaf(a):
        a = jnp.asarray(a)
        if a.ndim >= 2 and a.shape[1] == mb:
            return a.reshape((a.shape[0] * mb,) + a.shape[2:])
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return jnp.mean(a, axis=0)
        return a[-1]

    return jax.tree_util.tree_map(leaf, stacked)


def _checkpoint_policy(policy):
    """Resolve a validated remat-policy config value
    (`stats.remat_policy()`) to the jax.checkpoint policy callable.
    None stays None (checkpoint's own default = nothing saveable —
    but a None CONFIG means remat is OFF and no checkpoint wraps at
    all; callers branch on the config before resolving)."""
    from jax import checkpoint_policies as _cp

    if policy is None:
        return None
    if isinstance(policy, str):
        return getattr(_cp, policy)
    name, keep = policy  # ("save_anything_but_these_names", names)
    return _cp.save_anything_except_these_names(*keep)


class _JitForward:
    """Compiles `model.forward` into one XLA program (inference path).

    Same functionalization trick as `_JitStep` (via `_bound_model`),
    minus optimizer state and buffer donation (params are read-only
    here). The device RNG key is threaded through so eval-time
    stochastic ops stay reproducible. Layer-state updates made during a
    training-mode forward (BN running stats) are captured as program
    outputs and written back.

    Compiled executables are cached per (training-flag, non-Tensor
    args): the train/eval flag changes the traced program (dropout on /
    off), and plain-Python positional args are baked in as statics, not
    traced.

    Mesh mode: when the model was compiled over a mesh, inputs are laid
    out to match — params by the model's `ShardingRules`, states/key
    replicated, batch dims sharded — so the sharded train path and this
    eval path never mix incompatible device commitments.
    """

    def __init__(self, model: "Model"):
        self.model = model
        self.params: List[Tensor] = model.param_tensors()
        self.states: List[Tensor] = model.state_tensors()
        self._compiled: Dict = {}

    def _device(self):
        if self.params:
            return self.params[0].device
        from .device import get_default_device

        return get_default_device()

    def _build(self, tensor_pos, statics, nargs):
        model, params, states = self.model, self.params, self.states

        def fwd_fn(pvals, svals, key, batch):
            # int8 forward (ISSUE 19): quantized param leaves ride
            # the stream as (payload int8, scale f32) pairs —
            # dequantized once at program entry (fp32 accumulation
            # downstream). tuple-ness is the dispatch; the pytree
            # structure change retraces/orphans fp32 programs.
            pvals = [p[0].astype(p[1].dtype) * p[1]
                     if isinstance(p, tuple) else p for p in pvals]
            dev = self._device()
            with _bound_model(params, states, dev, pvals, svals, key):
                args = [None] * nargs
                for i, b in zip(tensor_pos, batch):
                    args[i] = tensor_mod.from_raw(b, dev)
                it = iter(statics)
                for i in range(nargs):
                    if args[i] is None:
                        args[i] = next(it)
                out_arrays = _unwrap_out(model.forward(*args))
                new_s = [s.data for s in states]
                return out_arrays, new_s, dev._rng_key

        return jax.jit(fwd_fn)

    def _quant_pvals(self, pvals):
        """Swap eligible param leaves for (payload, scale) pairs when
        int8 inference is armed (eval mode, single device). Host-side
        quantization is memoized per param buffer identity — a
        training step swaps the buffer and invalidates the entry.
        Small leaves (LN gammas, biases) stay fp32: no byte win, real
        precision cost."""
        from . import quant as quant_mod

        if (not quant_mod.enabled() or self.model.training
                or getattr(self.model, "_mesh", None) is not None):
            return pvals
        memo = getattr(self, "_quant_memo", None)
        if memo is None:
            memo = self._quant_memo = {}
        out = []
        for i, p in enumerate(pvals):
            if not quant_mod.forward_eligible(p):
                out.append(p)
                continue
            hit = memo.get(i)
            if hit is None or hit[0] is not p:
                memo[i] = (p, quant_mod.quantize_forward_leaf(p))
                quant_mod.stats_counters()["weights_quantized"] += 1
            out.append(memo[i][1])
        return out

    def _place_inputs(self, pvals, svals, key, batch_arrays):
        """Mesh-mode placement (single-device: identity)."""
        mesh = getattr(self.model, "_mesh", None)
        if mesh is None:
            return pvals, svals, key, batch_arrays
        from jax.sharding import NamedSharding

        from .parallel.sharding import (
            ShardingRules,
            batch_sharding,
            replicated,
        )

        rules = getattr(self.model, "_rules", None) or ShardingRules()
        name_of = {id(t): n for n, t in self.model.get_params().items()}
        pvals = [
            jax.device_put(
                v, rules.sharding_for(mesh, name_of.get(id(p), ""),
                                      p.data.shape))
            for p, v in zip(self.params, pvals)
        ]
        rep = replicated(mesh)
        svals = [jax.device_put(v, rep) for v in svals]
        key = jax.device_put(key, rep)
        specs = getattr(self.model, "_batch_specs", None)
        if specs is not None:
            shs = [NamedSharding(mesh, s) for s in specs]
        else:
            shs = [batch_sharding(mesh, getattr(b, "ndim", 0))
                   for b in batch_arrays]
        batch_arrays = tuple(
            jax.device_put(b, s) for b, s in zip(batch_arrays, shs)
        )
        return pvals, svals, key, batch_arrays

    def _export_identity(self, tensor_pos, statics, args):
        """(key, parts) of the AOT artifact a forward dispatch with
        these program args resolves to — the ONE definition shared by
        the dispatch path (`_obtain`) and the prewarm tool's dry-run
        probe (`export_key`), so the two can never drift."""
        from . import export_cache

        return export_cache.step_key(
            self.model, None, "forward", args,
            extras={"training": self.model.training,
                    "tensor_pos": list(tensor_pos),
                    # address-free: repr() of a plain object embeds
                    # its 0x... address and would make keys
                    # process-unique (never a warm hit)
                    "statics": [export_cache._scalarize(s)
                                for s in statics]})

    def export_key(self, *xs) -> str:
        """Store key of the artifact a `__call__` with these inputs
        would load — computed WITHOUT tracing, dispatching, or
        touching the hit/miss counters. Applies the same bucket
        padding `__call__` would, so feeding real (unbucketed) request
        shapes answers for the bucket they land in. Drives
        `tools/prewarm.py --dry-run` ("which (model, bucket) artifacts
        are missing?")."""
        from . import export_cache

        tensor_pos = tuple(i for i, x in enumerate(xs)
                           if isinstance(x, Tensor))
        statics = tuple(x for x in xs if not isinstance(x, Tensor))
        batch_arrays = tuple(xs[i].data for i in tensor_pos)
        if (export_cache.bucket_policy() is not None and batch_arrays
                and not self.model.training):
            batch_arrays, _ = export_cache.pad_batch_to_bucket(
                batch_arrays)
            batch_arrays = tuple(batch_arrays)
        dev = self._device()
        pvals, svals, key, batch_arrays = self._place_inputs(
            self._quant_pvals([p.data for p in self.params]),
            [s.data for s in self.states],
            dev._rng_key, batch_arrays,
        )
        args = (pvals, svals, key, batch_arrays)
        return self._export_identity(tensor_pos, statics, args)[0]

    def _obtain(self, cache_key, tensor_pos, statics, nargs, args):
        """Forward executable via the AOT store when armed: load the
        serialized artifact (no tracing) or trace once + publish —
        the serving-tier warm start, ONNX-imported models included."""
        from . import export_cache

        if not export_cache.active() or cache_key is None:
            fn = self._build(tensor_pos, statics, nargs)
            export_cache.count_trace(0.0)
            return fn
        key, parts = self._export_identity(tensor_pos, statics, args)
        exp = export_cache.load(key)
        if exp is None:
            built = self._build(tensor_pos, statics, nargs)
            exp = export_cache.export_and_save(key, parts, built, args)
            if exp is None:
                return built
        return jax.jit(exp.call)

    def __call__(self, *xs):
        from . import export_cache

        tensor_pos = tuple(i for i, x in enumerate(xs)
                           if isinstance(x, Tensor))
        statics = tuple(x for x in xs if not isinstance(x, Tensor))
        batch_arrays = tuple(xs[i].data for i in tensor_pos)
        # Pad-to-bucket at dispatch (ISSUE 6): under the pow2 policy a
        # stream of diverse batch/sequence sizes collapses onto at
        # most n_buckets() traced shapes; the padded rows/positions
        # (repeated final sample) are sliced back off the outputs
        # below (export_cache.slice_bucket_out — shape-inferred, the
        # _merge_accum_out caveat applies).
        # Training-mode forwards are NEVER padded: the program writes
        # BN running stats back from new_s, and stats over a padded
        # batch (final sample repeated) are reweighted state
        # corruption — the same contract as train_one_batch
        # ("training batches are not padded implicitly").
        bucket_info = None
        if (export_cache.bucket_policy() is not None and batch_arrays
                and not self.model.training):
            batch_arrays, bucket_info = \
                export_cache.pad_batch_to_bucket(batch_arrays)
            batch_arrays = tuple(batch_arrays)
            if (bucket_info["n_bucket"] == bucket_info["n_real"]
                    and bucket_info["seq_bucket"] ==
                    bucket_info["seq_real"]):
                bucket_info = None  # on bucket edges: nothing to slice
        try:
            from . import quant as _quant_mod

            cache_key = (self.model.training, tensor_pos, statics,
                         _quant_mod.mode())
            if export_cache.active():
                # serialized artifacts are shape-specialized: key the
                # executable cache per abstract batch signature
                cache_key += (tuple(
                    (tuple(int(d) for d in b.shape), str(b.dtype))
                    for b in batch_arrays),)
            fn = self._compiled.get(cache_key)
        except TypeError:  # unhashable static arg: compile fresh
            cache_key, fn = None, None
        dev = self._device()
        pvals, svals, key, batch_arrays = self._place_inputs(
            self._quant_pvals([p.data for p in self.params]),
            [s.data for s in self.states],
            dev._rng_key, batch_arrays,
        )
        if fn is None:
            fn = self._obtain(cache_key, tensor_pos, statics, len(xs),
                              (pvals, svals, key, batch_arrays))
            if cache_key is not None:
                self._compiled[cache_key] = fn
        out, new_s, new_key = fn(pvals, svals, key, batch_arrays)
        if bucket_info is not None:
            out = export_cache.slice_bucket_out(out, bucket_info)
        if self.model.training:
            for s, v in zip(self.states, new_s):
                s.data = v
        # Pin the advanced key back onto the device's own placement so
        # later eager code stays single-device even when params are
        # mesh-sharded (cf. _JitStep._restore_key).
        dev._rng_key = jax.device_put(new_key, dev.jax_device)
        return jax.tree_util.tree_map(
            lambda a: tensor_mod.from_raw(a, dev), out
        )


class _JitStep:
    """Compiles `model.train_one_batch` into a single XLA program.

    The functionalization trick: params, layer states (BN running
    stats), optimizer slots, and the device RNG key are *bound* to jit
    tracers before calling the user's Python `train_one_batch`, and
    their post-step values are collected as program outputs. Outside
    the trace, concrete arrays round-trip through the compiled
    executable with `donate_argnums` so XLA reuses the param HBM —
    the TPU equivalent of the reference scheduler's in-place Block
    update + memory reuse pass (src/core/scheduler/scheduler.cc).
    """

    def __init__(self, model: Model):
        from . import resilience

        self.model = model
        self.params: List[Tensor] = model.param_tensors()
        self.states: List[Tensor] = model.state_tensors()
        self.opt = model._optimizer
        self._compiled = None
        self._hlo_rows = None  # graph-profile cache (hlo_profile.py)
        # Export-cache state (ISSUE 6): one executable per abstract
        # batch signature when the AOT store is armed (a serialized
        # artifact is shape-specialized, unlike a polymorphic jit),
        # plus the seen-signature set behind the retrace-storm warning.
        self._by_sig: Dict = {}
        self._batch_sig = None
        self._seen_sigs = set()
        self._from_export = False
        # Gradient-accumulation factor baked into the built executable
        # (1 = off); read from the model/process knob at _build time —
        # toggling requires re-compile(), like donation/step-guard.
        self._accum_built = 1
        # Step-guard state (loss scale + counters) rides the flattened
        # opt-state slot of the jit signature, so the guard's skip /
        # backoff math updates on device with no extra program inputs.
        # Fixed at build time (like donation): toggling the guard
        # requires re-compile().
        self._guard_n = (len(resilience.state_arrays())
                         if resilience.guard_active() else 0)

    # ---- optimizer state flattening -------------------------------------
    def _opt_arrays(self):
        out = [] if self.opt is None else list(self.opt.state_arrays())
        if self._guard_n:
            from . import resilience

            out += resilience.state_arrays()
        return out

    def _bind_opt_arrays(self, arrays):
        arrays = list(arrays)
        if self._guard_n:
            from . import resilience

            resilience.bind_state_arrays(arrays[-self._guard_n:])
            arrays = arrays[:-self._guard_n]
        if self.opt is not None:
            self.opt.set_state_arrays(arrays)

    def _device(self):
        if self.params:
            return self.params[0].device
        from .device import get_default_device

        return get_default_device()

    def _build(self, *batch_arrays, donate=None):
        model, opt = self.model, self.opt
        params, states = self.params, self.states

        def step_fn(pvals, svals, ovals, key, step_counter, batch):
            saved_o = self._opt_arrays()
            dev = self._device()
            saved_step = None if opt is None else opt.step_counter
            with _bound_model(params, states, dev, pvals, svals, key):
                try:
                    self._bind_opt_arrays(ovals)
                    if opt is not None:
                        opt.step_counter = step_counter
                    batch_t = [tensor_mod.from_raw(b, dev) for b in batch]
                    out_arrays = _unwrap_out(model.train_one_batch(*batch_t))
                    new_p = [p.data for p in params]
                    new_s = [s.data for s in states]
                    new_o = self._opt_arrays()
                    new_key = dev._rng_key
                    return out_arrays, new_p, new_s, new_o, new_key
                finally:
                    self._bind_opt_arrays(saved_o)
                    if opt is not None and saved_step is not None:
                        opt.step_counter = saved_step

        # Pre-create optimizer slots so the jit signature (flattened
        # opt state) is stable from step one. step_counter is traced
        # (not static) so LR schedules don't retrigger compilation.
        self._ensure_opt_slots()
        # Gradient accumulation (ISSUE 4): n > 1 swaps the monolithic
        # step body for the scan-fused microbatch accumulator. Baked
        # at build time like donation; requires an optimizer (a
        # no-optimizer step has nothing to accumulate).
        n = self._accum_built = (self.model._accum_n()
                                 if self.opt is not None else 1)
        if n > 1:
            for b in batch_arrays:
                if getattr(b, "ndim", 0) < 1 or b.shape[0] % n:
                    raise ValueError(
                        f"grad_accum={n}: every batch input needs a "
                        f"leading dim divisible by {n}; got shape "
                        f"{getattr(b, 'shape', ())} — see "
                        "singa_tpu.data.microbatches")
            mb = batch_arrays[0].shape[0] // n
            stats_mod.note_accum_build(n, mb,
                                       batch_arrays[0].shape[0])

            def accum_fn(pvals, svals, ovals, key, step_counter,
                         batch):
                return self._accum_step(n, pvals, svals, ovals, key,
                                        step_counter, batch)

            step_fn = accum_fn
        elif (stats_mod.remat_policy() is not None
              and self.opt is not None):
            # Scan-level remat with accumulation OFF (ISSUE 9): the
            # whole batch runs as ONE checkpointed microbatch through
            # the accumulation body (length-1 scan elided inside
            # _accum_scan), so the policy has exactly one definition
            # whether or not grad accumulation is on. Requires the
            # accumulation contract (one backward_and_update per
            # step), which _accum_step validates.
            def remat_fn(pvals, svals, ovals, key, step_counter,
                         batch):
                return self._accum_step(1, pvals, svals, ovals, key,
                                        step_counter, batch)

            step_fn = remat_fn
        # Donation honors the eager-config knob at build time
        # (device.set_buffer_donation); re-compile() to re-arm. The
        # export-cache path forces donation OFF (`donate=False`): a
        # deserialized artifact executes through `Exported.call`,
        # whose caller never donates, and an aliased-input module
        # without donated buffers would silently invalidate arrays the
        # Python side still holds.
        if donate is None:
            donate = stats_mod.donation_enabled()
        donate_argnums = (0, 1, 2, 3) if donate else ()
        return jax.jit(step_fn, donate_argnums=donate_argnums,
                       **self._jit_kwargs(batch_arrays))

    def _jit_kwargs(self, batch_arrays):
        """Hook for sharded subclasses (parallel.trainer.ShardedJitStep)
        to add in/out shardings over a mesh."""
        return {}

    # ---- gradient accumulation (ISSUE 4) ---------------------------------
    def _microbatch_stack(self, n, batch):
        """Reshape every batch array [B, ...] → [n, B/n, ...] (the
        scan axis first). Divisibility is validated at _build;
        re-validated here because jit retraces on new shapes."""
        out = []
        for b in batch:
            if getattr(b, "ndim", 0) < 1 or b.shape[0] % n:
                raise ValueError(
                    f"grad_accum={n}: batch shape "
                    f"{getattr(b, 'shape', ())} has no leading dim "
                    f"divisible by {n}")
            out.append(b.reshape((n, b.shape[0] // n)
                                 + tuple(b.shape[1:])))
        return self._place_microbatches(out)

    def _place_microbatches(self, micro):
        """Hook: sharded subclasses constrain the microbatch layout
        ([n] replicated, batch dims sharded); identity on one
        device."""
        return micro

    def _run_accum_microbatch(self, dev, svals_c, key_c, mb,
                              skip_backward: bool = False):
        """One microbatch forward+backward with the optimizer in
        capture mode: binds states/key, runs the user's
        train_one_batch, and returns (out_arrays, loss_array, pairs,
        new_state_arrays, new_key). The shared body of the discovery
        pass, the scan body, and the sharded local step.

        `skip_backward=True` (the scan-level remat path) runs the
        forward+loss only — `pairs` comes back None and the caller
        derives gradients from `jax.vjp` over the checkpointed
        region (`_remat_microbatch_grads`)."""
        import jax.numpy as jnp

        model, opt = self.model, self.opt
        for s, v in zip(self.states, svals_c):
            s.data = v
        dev._rng_key = key_c
        opt._accum_begin(skip_backward=skip_backward)
        try:
            out = model.train_one_batch(
                *[tensor_mod.from_raw(b, dev) for b in mb])
        finally:
            cap = opt._accum_end()
        if len(cap) != 1:
            raise RuntimeError(
                "gradient accumulation requires train_one_batch to "
                "call backward_and_update exactly once per "
                f"microbatch; it ran {len(cap)} times")
        loss_t, pairs = cap[0]
        loss_arr = jnp.asarray(
            loss_t.data if isinstance(loss_t, Tensor) else loss_t)
        return (_unwrap_out(out), loss_arr, pairs,
                [s.data for s in self.states], dev._rng_key)

    def _discover_accum_order(self, dev, svals, key, mb_specs):
        """Learn which params receive gradients — and in what emission
        order — by abstractly evaluating ONE microbatch
        forward+backward under `jax.eval_shape` (no XLA compile, no
        execution; the same zero-cost trick as the eval_shape param
        init). The order fixes the scan carry structure. Also returns
        the abstract per-microbatch output pytree
        (jax.ShapeDtypeStruct leaves) — the sharded accumulation path
        derives its shard_map out_specs from it. All bound state is
        restored afterwards."""
        saved_s = [s.data for s in self.states]
        saved_key = dev._rng_key
        order = []

        def probe(svals_c, key_c, mb):
            outs, _, pairs, _, _ = self._run_accum_microbatch(
                dev, svals_c, key_c, mb)
            order[:] = [p for p, _ in pairs]
            return outs

        try:
            outs_sds = jax.eval_shape(probe, svals, key, mb_specs)
        finally:
            for s, v in zip(self.states, saved_s):
                s.data = v
            dev._rng_key = saved_key
        if not order:
            raise RuntimeError(
                "gradient accumulation: the backward produced no "
                "(param, grad) pairs — nothing to accumulate")
        return order, outs_sds

    def _remat_microbatch_grads(self, dev, order, svals_c, key_c, mb,
                                policy):
        """One microbatch under the scan-level remat policy (ISSUE 9):
        the ENTIRE forward+loss region — the user's train_one_batch
        with the framework backward suppressed — is wrapped in
        `jax.checkpoint(policy=...)` and gradients come from ONE
        `jax.vjp` over it, so what survives the fwd→bwd boundary is
        exactly the policy's saveable set (region inputs + e.g. dot
        results under `dots_saveable`) instead of every op's
        residuals; XLA recomputes the rest inside the backward. The
        vjp seed matches `backward_and_update`'s (the live loss scale
        under dynamic scaling, implicit ones otherwise), so the grads
        feed `apply_accumulated` identically to the captured-pairs
        path. Returns (out_arrays, loss_array, grads_in_order,
        new_state_arrays, new_key)."""
        import jax.numpy as jnp

        from . import resilience

        params = order

        def region(plist, sv, kv, mb_arrays):
            saved = [p.data for p in params]
            try:
                for p, v in zip(params, plist):
                    p.data = v
                outs, loss_arr, _, new_s, new_key = \
                    self._run_accum_microbatch(dev, sv, kv, mb_arrays,
                                               skip_backward=True)
            finally:
                for p, v in zip(params, saved):
                    p.data = v
            return loss_arr, (outs, tuple(new_s), new_key)

        ck = jax.checkpoint(region, policy=_checkpoint_policy(policy))
        plist = [p.data for p in params]
        loss_arr, vjp_fn, aux = jax.vjp(ck, plist, list(svals_c),
                                        key_c, list(mb), has_aux=True)
        outs, new_s, new_key = aux
        if resilience.guard_active() and resilience.scaler_active():
            seed = resilience.scaled_seed(loss_arr)
        else:
            seed = jnp.ones_like(loss_arr)
        grads = vjp_fn(seed)[0]
        return outs, loss_arr, list(grads), list(new_s), new_key

    def _accum_scan(self, dev, order, svals_init, key_init, micro):
        """`lax.scan` the user's train_one_batch over a [n, mb, ...]
        microbatch stack, accumulating gradients in fp32. The ONE
        definition of the accumulation loop body — the single-device
        step and the sharded shard_map local step both run exactly
        this, so the modes cannot drift apart numerically. Under
        `device.set_remat_policy` the body's gradients come from the
        checkpointed-region vjp (`_remat_microbatch_grads`) instead of
        the captured per-op walk — same accumulation math either way.
        Returns ((final_states, final_key, grad_sums, loss_sum),
        stacked_outs)."""
        import jax.numpy as jnp

        acc0 = [jnp.zeros(p.data.shape, jnp.float32) for p in order]
        ids = [id(p) for p in order]
        remat_pol = stats_mod.remat_policy()

        def body(carry, mb_arrays):
            svals_c, key_c, acc, loss_acc = carry
            if remat_pol is not None:
                outs, loss_arr, gl, new_s, new_key = \
                    self._remat_microbatch_grads(dev, order, svals_c,
                                                 key_c, mb_arrays,
                                                 remat_pol)
            else:
                outs, loss_arr, pairs, new_s, new_key = \
                    self._run_accum_microbatch(dev, svals_c, key_c,
                                               mb_arrays)
                gd = {id(p): (g.data if isinstance(g, Tensor) else g)
                      for p, g in pairs}
                if sorted(gd) != sorted(ids):
                    raise RuntimeError(
                        "gradient accumulation: the (param, grad) set "
                        "changed between the discovery pass and the "
                        "scan body")
                gl = [gd[i] for i in ids]
            # same sequential fp32 sum as the eager adder
            # (_accum_add_fn) — the two modes accumulate
            # bit-identically
            acc = [a + g.astype(jnp.float32)
                   for a, g in zip(acc, gl)]
            loss_acc = loss_acc + jnp.mean(loss_arr).astype(
                jnp.float32)
            return (tuple(new_s), new_key, acc, loss_acc), outs

        carry0 = (tuple(svals_init), key_init, acc0,
                  jnp.zeros((), jnp.float32))
        if micro and int(micro[0].shape[0]) == 1:
            # Length-1 "scan" (the remat-policy reroute of a
            # non-accumulated step): run the body once inline — no
            # while loop in the HLO, so the entry-level byte/peak
            # meters stay sighted on the step's real internals.
            carry, outs = body(carry0, [m[0] for m in micro])
            outs = jax.tree_util.tree_map(
                lambda a: jnp.asarray(a)[None], outs)
            return carry, outs
        return jax.lax.scan(body, carry0, micro)

    def _accum_step(self, n, pvals, svals, ovals, key, step_counter,
                    batch):
        """The scan-fused accumulation step body: reshape the batch to
        [n, mb, ...], `lax.scan` the user's train_one_batch over the
        microbatches — layer states (BN running stats) and the RNG key
        thread through the carry, gradients accumulate in fp32 — then
        apply the optimizer exactly once on the mean via
        `opt.apply_accumulated` (StepGuard cond, scaler unscale,
        global-norm clip, and bf16 slot quantization all fire once on
        the accumulated grads). XLA keeps the live activation/gradient
        footprint at microbatch size: only the fp32 accumulator (one
        param-sized set of arrays) persists across iterations."""
        import jax.numpy as jnp

        model, opt = self.model, self.opt
        params, states = self.params, self.states
        dev = self._device()
        saved_o = self._opt_arrays()
        saved_step = opt.step_counter
        with _bound_model(params, states, dev, pvals, svals, key):
            try:
                self._bind_opt_arrays(ovals)
                opt.step_counter = step_counter
                micro = self._microbatch_stack(n, batch)
                mb = micro[0].shape[1]
                mb_specs = [jax.ShapeDtypeStruct(m.shape[1:], m.dtype)
                            for m in micro]
                order, _ = self._discover_accum_order(dev, svals, key,
                                                      mb_specs)
                (svals_f, key_f, acc, loss_sum), outs = \
                    self._accum_scan(dev, order, svals, key, micro)
                # rebind the post-scan values (the body's in-trace
                # mutations died with the scan trace)
                for s, v in zip(states, svals_f):
                    s.data = v
                dev._rng_key = key_f
                opt.apply_accumulated(loss_sum,
                                      list(zip(order, acc)), n)
                out_arrays = _merge_accum_out(outs, mb)
                new_p = [p.data for p in params]
                new_s = [s.data for s in states]
                new_o = self._opt_arrays()
                new_key = dev._rng_key
                return out_arrays, new_p, new_s, new_o, new_key
            finally:
                self._bind_opt_arrays(saved_o)
                opt.step_counter = saved_step

    def _prepare_inputs(self, pvals, svals, ovals, key, batch_arrays):
        """Hook: place program inputs (sharded subclasses device_put
        onto the mesh; identity on one device)."""
        return pvals, svals, ovals, key, batch_arrays

    def _restore_key(self, new_key, dev):
        """Hook: the updated RNG key's placement. Sharded subclasses
        bring it back to the device's own placement so later eager code
        (fresh param init, dropout outside jit) stays single-device."""
        return new_key

    def _ensure_opt_slots(self):
        """Create optimizer state slots with zero arrays so the jit
        signature (flattened opt state) is stable from step one."""
        import jax.numpy as jnp

        if self.opt is None:
            return
        opt = self.opt
        base = getattr(opt, "opt", opt)  # DistOpt wraps
        from .opt import Adam, AdaGrad, RMSProp, SGD

        def zeros(name, p):
            # honors the optimizer's slot_dtype policy (byte diet):
            # half-width slots enter the jit signature half-width
            return jnp.zeros(p.data.shape,
                             base.slot_store_dtype(name, p))

        for p in self.params:
            st = base.states.setdefault(id(p), {})
            if isinstance(base, SGD) and base.momentum and "momentum_buf" not in st:
                # zero buf + buf=m*buf+(1-damp)*g reproduces the lazy
                # first step (buf=g) exactly when dampening==0; with
                # dampening>0 the first graph-mode step deviates by the
                # dampening factor (documented limitation).
                st["momentum_buf"] = zeros("momentum_buf", p)
            elif isinstance(base, RMSProp) and "running_avg" not in st:
                st["running_avg"] = zeros("running_avg", p)
            elif isinstance(base, AdaGrad) and "history" not in st:
                st["history"] = zeros("history", p)
            elif isinstance(base, Adam):
                st.setdefault("m", zeros("m", p))
                st.setdefault("v", zeros("v", p))

    def lowered_text(self, *batch, optimized: bool = True) -> str:
        """HLO text of the compiled train step for these batch shapes
        (no execution, no donation hazard — .lower() only reads
        shapes). `optimized=True` (default) returns the
        post-optimization text — the input to
        `hlo_profile.bytes_accessed`, the CPU-verifiable byte-diet
        meter. `optimized=False` returns the PRE-optimization HLO
        (`dialect="hlo"`, no XLA compile paid): the text where
        `jax.checkpoint`'s optimization barriers still stand — the
        CPU backend's cleanup passes CSE the remat recompute away
        post-optimization (CPU has no HBM to save), so the remat
        knob's liveness effect (`hlo_profile.peak_bytes_estimate`) is
        only honest pre-optimization, which is also the program the
        TPU compiler (which honors the barriers) actually sees."""
        batch_arrays = tuple(
            b.data if isinstance(b, Tensor) else b for b in batch
        )
        if self._compiled is None:
            self._compiled = self._build(*batch_arrays)
        dev = self._device()
        pvals = [p.data for p in self.params]
        svals = [s.data for s in self.states]
        ovals = self._opt_arrays()
        step = 0 if self.opt is None else self.opt.step_counter
        pvals, svals, ovals, key, batch_arrays = self._prepare_inputs(
            pvals, svals, ovals, dev._rng_key, batch_arrays
        )
        lowered = self._compiled.lower(
            pvals, svals, ovals, key, step, batch_arrays
        )
        if not optimized:
            return lowered.as_text(dialect="hlo")
        return lowered.compile().as_text()

    # ---- AOT export cache (ISSUE 6) --------------------------------------
    def _export_kind(self) -> str:
        return "step"

    def _export_extras(self):
        """Hook: per-subclass key identity (the sharded step adds its
        mesh layout). None on one device."""
        return None

    def _note_batch_sig(self, batch_arrays):
        """Track the abstract batch signature across calls. Returns
        the PRIOR signature when this one is new-after-warmup (the
        retrace-storm precondition) else None — the caller fires
        `export_cache.note_step_retrace` only where a trace is
        actually imminent (plain-jit new shape, or an export-store
        MISS): a warm artifact LOAD of a new shape is not a retrace
        and must not alarm the provisioning counter."""
        sig = tuple(
            (tuple(int(d) for d in getattr(b, "shape", ())),
             str(getattr(b, "dtype", type(b).__name__)))
            for b in batch_arrays)
        prior = None
        if (self._batch_sig is not None and sig != self._batch_sig
                and sig not in self._seen_sigs):
            prior = self._batch_sig
        self._seen_sigs.add(sig)
        self._batch_sig = sig
        return prior

    def _note_warm_geometry(self, batch_arrays):
        """A warm-loaded artifact skips _build, so re-derive the
        bookkeeping _build would have done: the accumulation factor
        baked into the artifact (the key guarantees it matches the
        live knob) and its microbatch geometry counters."""
        n = self.model._accum_n() if self.opt is not None else 1
        self._accum_built = n
        if (n > 1 and batch_arrays
                and getattr(batch_arrays[0], "ndim", 0) >= 1
                and batch_arrays[0].shape[0] % n == 0):
            b = int(batch_arrays[0].shape[0])
            stats_mod.note_accum_build(n, b // n, b)

    def _obtain_export(self, args, batch_arrays, prior_sig=None):
        """Export-cache path: one executable per batch signature —
        load the serialized artifact when one exists (millisecond warm
        start, zero tracing), else trace once, serialize, and publish
        so every later process warm-starts. Falls back to the plain
        jit loudly when the program cannot be exported. `prior_sig`
        (a new-after-warmup signature's predecessor) arms the
        retrace-storm warning — fired only on a store MISS, where a
        trace is actually paid."""
        import jax as _jax

        from . import export_cache

        fn = self._by_sig.get(self._batch_sig)
        if fn is not None:
            return fn
        # The knob snapshot records the PROCESS grad_accum knob; the
        # effective factor can differ per model (compile(grad_accum=n)
        # overrides it) and bakes a different program — it must key.
        extras = {"accum": (self.model._accum_n()
                            if self.opt is not None else 1),
                  "subclass": self._export_extras()}
        key, parts = export_cache.step_key(
            self.model, self.opt, self._export_kind(), args,
            extras=extras)
        exp = export_cache.load(key)
        if exp is not None:
            self._note_warm_geometry(batch_arrays)
            fn = _jax.jit(exp.call)
        else:
            if prior_sig is not None:
                export_cache.note_step_retrace(prior_sig,
                                               self._batch_sig)
            built = self._build(*batch_arrays, donate=False)
            exp = export_cache.export_and_save(key, parts, built, args)
            fn = _jax.jit(exp.call) if exp is not None else built
        self._by_sig[self._batch_sig] = fn
        return fn

    def __call__(self, *batch: Tensor):
        from . import export_cache

        batch_arrays = tuple(
            b.data if isinstance(b, Tensor) else b for b in batch
        )
        prior_sig = self._note_batch_sig(batch_arrays)
        dev = self._device()
        opt = self.opt
        exporting = export_cache.active()
        if not exporting:
            if self._from_export:
                # the store was disarmed mid-run: the held executable
                # is shape-SPECIALIZED (Exported.call rejects new
                # shapes where a polymorphic jit would retrace) —
                # rebuild plain
                self._compiled = None
                self._from_export = False
            if prior_sig is not None and self._compiled is not None:
                # the polymorphic jit is about to retrace internally
                export_cache.note_step_retrace(prior_sig,
                                               self._batch_sig)
            if self._compiled is None:
                self._compiled = self._build(*batch_arrays)
                export_cache.count_trace(0.0)
        if exporting and self._batch_sig not in self._by_sig:
            # signature must be stable before arrays are collected:
            # slots are pre-created here exactly as _build would
            self._ensure_opt_slots()
        pvals = [p.data for p in self.params]
        svals = [s.data for s in self.states]
        ovals = self._opt_arrays()
        step = 0 if opt is None else opt.step_counter
        pvals, svals, ovals, key, batch_arrays = self._prepare_inputs(
            pvals, svals, ovals, dev._rng_key, batch_arrays
        )
        if exporting:
            self._compiled = self._obtain_export(
                (pvals, svals, ovals, key, step, batch_arrays),
                batch_arrays, prior_sig=prior_sig)
            self._from_export = True
        profiling = dev._verbosity > 0
        if profiling and getattr(self, "_hlo_rows", None) is None:
            # One extra lower+compile (shapes only — safe before the
            # donating call below) yields the optimized HLO for the
            # per-op cost table (hlo_profile.py).
            try:
                from . import hlo_profile

                text = self._compiled.lower(
                    pvals, svals, ovals, key, step, batch_arrays
                ).compile().as_text()
                self._hlo_rows = hlo_profile.profile_hlo(text)
            except Exception:
                self._hlo_rows = []
        t0 = time.perf_counter() if profiling else 0.0
        # dispatch: host time to enqueue the compiled program (first
        # call: trace+compile). device_sync below only exists while
        # tracing — an unconditional fence would break the pipelined
        # steady state this step is designed for.
        with trace_mod.span("dispatch"):
            out, new_p, new_s, new_o, new_key = self._compiled(
                pvals, svals, ovals, key, step, batch_arrays
            )
        if trace_mod.enabled():
            with trace_mod.span("device_sync"):
                jax.block_until_ready(new_key)
        # Accumulated replays count their n microbatch invocations so
        # train_steps agrees between eager and graph accumulation;
        # accum_steps counts the one executed apply (the in-trace
        # counter in apply_accumulated only fires on concrete values).
        stats_mod.count_train_step(max(1, self._accum_built))
        if self._accum_built > 1:
            stats_mod.count_accum_step()
        if profiling:
            jax.block_until_ready(new_key)
            dt = time.perf_counter() - t0
            dev.StepIteration()  # graph replay == one iteration (ref)
            dev.RecordOpTime("train_one_batch[graph]", dt)
            # Keyed per model so two compiled models on one device
            # (e.g. a GAN's G and D) keep separate tables.
            label = f"train_one_batch:{self.model.name or 'model'}" \
                    f"@{id(self.model) & 0xffff:04x}"
            prof = dev._graph_profiles.setdefault(
                label, {"rows": self._hlo_rows or [], "step_s": dt})
            prof["step_s"] = min(prof["step_s"], dt)
            prof["rows"] = self._hlo_rows or []
        for p, v in zip(self.params, new_p):
            p.data = v
        for s, v in zip(self.states, new_s):
            s.data = v
        self._bind_opt_arrays(new_o)
        dev._rng_key = self._restore_key(new_key, dev)
        if opt is not None:
            opt.step_counter = step + 1
        return jax.tree_util.tree_map(
            lambda a: tensor_mod.from_raw(a, dev), out
        )
