"""AOT executable cache: persistent `jax.export` artifacts + the
shape-bucketing policy (ISSUE 6; ROADMAP item 4).

Why: a fleet of training/serving workers cannot pay Python tracing +
XLA compilation per process or per novel batch shape — r05 burned
~73 s per probe on recompiles before the compile-cache env export, and
the persistent XLA cache only removes the *compile* half. This module
removes the *trace* half: the whole-step (`model._JitStep`), mesh-step
(`parallel.trainer.ShardedJitStep`), and forward-only
(`model._JitForward`) executables are serialized with `jax.export`
into an on-disk store, and a fresh process deserializes the StableHLO
artifact instead of re-tracing the user's Python — milliseconds where
tracing took seconds. PHAST (arXiv:2005.13076) and the GPU-to-CPU
transpilation work (arXiv:2207.00257) both argue for portable compiled
artifacts as the interchange point between build time and run time;
`jax.export`'s versioned StableHLO is exactly that artifact here.

Keying: an artifact may load ONLY when it would trace identically.
The key hashes (a) the model topology fingerprint
(`Model.topology_fingerprint`: class + source + param/state inventory;
`sonnx.SONNXModel` overrides with the ONNX graph digest, so imported
graphs warm-start too), (b) the abstract argument signature
(shapes/dtypes/tree structure — post-bucketing, so the bucket IS the
key), (c) a snapshot of every step-affecting knob — remat policy, slot
dtype, BN-stats dtype, grad-accum geometry, step guard, loss scaling,
XLA profile, AMP compute dtype, matmul precision, optimizer
hyperparameters — and (d) the platform: jax version, backend, device
kind, device count, plus mesh extras for sharded steps. A knob change
changes the key; a stale artifact can never load.

Integrity: every artifact gets a digest manifest sidecar (sha256 +
size, the `checkpoint.CheckpointManager` idiom). A corrupt/truncated
artifact is reported loudly and the caller falls back to tracing —
a bad cache entry costs one trace, never a wrong program.
`tools/export_cache_gc.py` lists / validates / garbage-collects the
store.

Bucketing: `BucketPolicy` rounds batch (and optionally sequence) dims
up to the next power of two, bounded by an explicit maximum — a shape
above the largest bucket is a LOUD error, not a silent retrace.
`pad_batch_to_bucket` pads at dispatch by repeating the final sample
(`data.microbatches`' pad idiom); the forward path slices padded rows
back off, so under diverse traffic the number of distinct traced
shapes — and therefore retraces and artifacts — is bounded by the
bucket count. Counters: `cache_stats()["export"]` (hits / misses /
saves / errors / traces / load_s / trace_s / bucket_pads /
buckets_seen / step_retraces).

Knobs: `device.set_export_cache(dir)` arms the store;
`device.set_shape_buckets(max_batch=..., seq_dim=..., max_seq=...)`
arms the bucketing policy (each works without the other).
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from . import stats as stats_mod

__all__ = [
    "BucketPolicy",
    "BucketOverflowError",
    "configure",
    "active",
    "bucket_policy",
    "pad_batch_to_bucket",
    "pad_batch",
    "batch_mask",
    "step_key",
    "artifact_exists",
    "load",
    "export_and_save",
    "note_step_retrace",
    "list_artifacts",
    "validate_artifact",
]

# Artifact schema version: bump to orphan every prior artifact (key
# component, not a runtime check).
SCHEMA = 1

_CONFIG: Dict = {
    # Artifact store directory (None = cache off).
    "directory": None,
    # BucketPolicy or None (bucketing works independently of the store:
    # without a directory it still bounds live retraces).
    "buckets": None,
}


class BucketOverflowError(ValueError):
    """A dispatched shape exceeds the largest configured bucket.

    Deliberately loud: silently tracing an unbounded shape is exactly
    the retrace storm the policy exists to prevent — the caller must
    either raise the bucket ceiling or reject the request."""


class BucketPolicy:
    """Powers-of-two shape buckets with explicit ceilings.

    `max_batch` bounds the batch (dim 0) bucket ladder; `seq_dim` /
    `max_seq` optionally bucket a sequence dimension too (right-pad
    semantics — safe for causal attention, where later positions never
    influence earlier ones; bidirectional models should bucket batch
    only). Ceilings must be powers of two so the ladder has no
    unreachable gap between the top bucket and the ceiling.
    """

    def __init__(self, max_batch: int = 4096,
                 seq_dim: Optional[int] = None,
                 max_seq: Optional[int] = None):
        self.max_batch = int(max_batch)
        self.seq_dim = None if seq_dim is None else int(seq_dim)
        self.max_seq = None if max_seq is None else int(max_seq)
        for name, v in (("max_batch", self.max_batch),
                        ("max_seq", self.max_seq)):
            if v is not None and (v < 1 or v & (v - 1)):
                raise ValueError(
                    f"BucketPolicy {name} must be a power of two >= 1, "
                    f"got {v}")
        if self.seq_dim is not None and self.max_seq is None:
            raise ValueError("seq_dim set but max_seq missing")
        if self.max_seq is not None and self.seq_dim is None:
            # the converse is equally a silent misconfiguration: a
            # ceiling with no dimension to bucket is dead code the
            # caller believes is armed
            raise ValueError("max_seq set but seq_dim missing")

    @staticmethod
    def _bucket(n: int, ceiling: int, what: str) -> int:
        if n < 1:
            raise ValueError(f"cannot bucket empty {what} dim ({n})")
        if n > ceiling:
            raise BucketOverflowError(
                f"{what} size {n} exceeds the largest configured "
                f"bucket ({ceiling}); raise the ceiling "
                "(device.set_shape_buckets) or reject the request — "
                "silently tracing an unbounded shape defeats the "
                "bucketing policy")
        b = 1
        while b < n:
            b <<= 1
        return b

    def bucket_batch(self, n: int) -> int:
        return self._bucket(int(n), self.max_batch, "batch")

    def bucket_seq(self, n: int) -> int:
        return self._bucket(int(n), self.max_seq, "sequence")

    def n_buckets(self) -> int:
        """Upper bound on distinct bucketed shapes per dimension set:
        len({1, 2, 4, ..., max_batch}) x len(seq ladder)."""
        out = self.max_batch.bit_length()
        if self.max_seq is not None:
            out *= self.max_seq.bit_length()
        return out

    def describe(self) -> Dict:
        return {"max_batch": self.max_batch, "seq_dim": self.seq_dim,
                "max_seq": self.max_seq}


def configure(**kw) -> Dict:
    """Update export-cache knobs (`directory`, `buckets`). User-facing
    setters live on `singa_tpu.device` (`set_export_cache`,
    `set_shape_buckets`)."""
    for k, v in kw.items():
        if k not in _CONFIG:
            raise KeyError(
                f"unknown export_cache config key {k!r}; known: "
                f"{sorted(_CONFIG)}")
        if k == "directory" and v is not None:
            v = str(v) or None  # "" means off (the env-var contract)
            if v is not None:
                os.makedirs(v, exist_ok=True)
        if k == "buckets" and v is not None and not isinstance(
                v, BucketPolicy):
            raise ValueError("buckets must be a BucketPolicy or None")
        _CONFIG[k] = v
    return dict(_CONFIG)


def active() -> bool:
    return _CONFIG["directory"] is not None


def directory() -> Optional[str]:
    """The armed store directory (None when the cache is off) — what
    a fleet parent hands its worker subprocesses so every replica
    deserializes from the SAME store (populate-once-start-N)."""
    return _CONFIG["directory"]


def bucket_policy() -> Optional[BucketPolicy]:
    return _CONFIG["buckets"]


# ---------------------------------------------------------------------------
# Observability: cache_stats()["export"]
# ---------------------------------------------------------------------------
class _ExportStats:
    """Counters for the AOT artifact store + bucketing policy.

    `traces` counts step/forward executables actually TRACED in this
    process (the cost warm starts avoid — a fully warm process shows
    traces=0); `load_s`/`trace_s` are the cumulative wall seconds the
    two paths cost, which is how bench.py splits its `compile` stage
    second into trace/compile/load. `step_retraces` counts post-warmup
    abstract-shape changes on the step path (the retrace-storm
    warning's counter). `buckets_seen` is the number of distinct
    bucketed dispatch shapes — under the policy it is bounded by
    `BucketPolicy.n_buckets()`, which is what turns the retrace
    counter into a provisioning signal."""

    def __init__(self):
        self.reset()
        self._buckets = set()

    def reset(self) -> None:
        self.hits = 0
        self.misses = 0
        self.saves = 0
        self.errors = 0
        self.traces = 0
        self.step_retraces = 0
        self.bucket_pads = 0
        self.load_s = 0.0
        self.trace_s = 0.0
        # buckets_seen describes live dispatch diversity, reset with
        # the counters (a fresh measurement window starts clean)
        self._buckets = set()

    def note_bucket(self, sig) -> None:
        self._buckets.add(sig)

    def snapshot(self) -> Dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "saves": self.saves,
            "errors": self.errors,
            "traces": self.traces,
            "step_retraces": self.step_retraces,
            "bucket_pads": self.bucket_pads,
            "buckets_seen": len(self._buckets),
            "load_s": round(self.load_s, 6),
            "trace_s": round(self.trace_s, 6),
            "dir": _CONFIG["directory"] or "",
        }


_STATS = _ExportStats()
stats_mod.register_cache("export", _STATS)


def export_stats() -> _ExportStats:
    return _STATS


# ---------------------------------------------------------------------------
# Key computation
# ---------------------------------------------------------------------------
def _scalarize(v, depth: int = 2):
    """JSON-able, ADDRESS-FREE projection of a config value: scalars
    pass through, containers recurse, callables key on their code (two
    different schedules/statics must not collide), arrays on
    shape/dtype/content digest, and other objects flatten to class
    name + scalar attrs (one level) — `repr` would embed `0x...`
    addresses and make keys process-unique, which would defeat the
    cache."""
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return [_scalarize(x, depth) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted(str(x) for x in v)
    if isinstance(v, dict):
        return {str(k): _scalarize(x, depth) for k, x in sorted(v.items())}
    code = getattr(v, "__code__", None)
    if code is not None:
        # plain function/lambda: identity = name + bytecode + embedded
        # constants (two lambdas differing only in a literal must not
        # collide)
        return {"__callable__": f"{getattr(v, '__module__', '')}."
                                f"{getattr(v, '__qualname__', '')}",
                "code": hashlib.sha256(code.co_code).hexdigest(),
                "consts": [_scalarize(c, 0) for c in code.co_consts]}
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        import numpy as np

        arr = np.asarray(v)
        return {"__array__": [list(map(int, arr.shape)),
                              str(arr.dtype)],
                "sha256": (hashlib.sha256(arr.tobytes()).hexdigest()
                           if arr.size <= (1 << 20) else None)}
    if depth <= 0:
        return type(v).__name__
    # objects — callable instances (LR schedules) included: their
    # hyperparameters live in __dict__ and MUST key (an artifact bakes
    # the schedule's constants into the traced program), and their
    # behavior lives in __call__'s code
    out = {"__class__": type(v).__name__}
    call_code = getattr(getattr(type(v), "__call__", None), "__code__",
                        None)
    if callable(v) and call_code is not None:
        out["__call_code__"] = hashlib.sha256(
            call_code.co_code).hexdigest()
    for k, a in sorted(getattr(v, "__dict__", {}).items()):
        out[k] = _scalarize(a, depth - 1)
    return out


def _opt_fingerprint(opt):
    """Optimizer identity for the key: class + every scalar
    hyperparameter (lr, momentum, weight decay, slot dtype, schedule
    params...). Runtime state is excluded — `states` and
    `step_counter` are program INPUTS, not program structure."""
    if opt is None:
        return None
    out = {"class": type(opt).__name__}
    targets = [("", opt)]
    inner = getattr(opt, "opt", None)
    if inner is not None and inner is not opt:
        targets.append(("inner.", inner))
    for prefix, o in targets:
        for k, v in sorted(getattr(o, "__dict__", {}).items()):
            if k in ("states", "step_counter", "opt") or k.startswith(
                    "_fused") or k.startswith("_accum"):
                continue
            out[prefix + k] = _scalarize(v)
    return out


def knob_fingerprint() -> Dict:
    """Snapshot of every process knob that changes the traced step:
    the contract that makes a stale artifact unloadable."""
    from . import autograd, device, tensor

    from .ops import pallas_kernels

    cfg = stats_mod.get_config()
    remat = getattr(autograd, "_remat", False)
    return {
        # train/eval mode: dropout and BatchNorm trace DIFFERENT
        # programs (eval BN normalizes by running stats and never
        # updates them) — a train-mode forward artifact silently
        # reused for inference would be a correctness bug, so the mode
        # rides the knob snapshot for every executable kind, not just
        # the forward extras.
        "train_mode": bool(autograd.training),
        # pallas tier: flash-attention vs plain attention are
        # DIFFERENT traced programs behind the same model code
        "pallas": pallas_kernels.enabled(),
        # stats-owned step-affecting knobs (dag cache capacity/policy
        # and the eager auto-route threshold do NOT change the traced
        # graph-mode program and are deliberately excluded)
        "bn_stats_dtype": cfg.get("bn_stats_dtype"),
        "step_guard": cfg.get("step_guard"),
        "loss_scaling": _scalarize(cfg.get("loss_scaling")),
        "grad_accum": cfg.get("grad_accum"),
        "remat": _scalarize(remat),
        # scan-level remat policy (ISSUE 9): a policy flip re-derives
        # the backward (checkpointed-region vjp vs captured walk) —
        # a different traced program, so it must orphan artifacts
        "remat_policy": _scalarize(cfg.get("remat_policy")),
        "compute_dtype": str(tensor.get_compute_dtype()),
        "matmul_precision": tensor.get_matmul_precision(),
        "xla_profile": device.get_xla_profile(),
        # Multi-axis trainer knobs (ISSUE 10): the process-default
        # ParallelPlan selects mesh/schedule at compile time, and the
        # pipeline-microbatch / MoE-capacity overrides change the
        # traced schedule geometry — all three must orphan artifacts
        # on flip (a per-model compile(plan=...) rides the sharded
        # step's extras instead).
        "parallel_plan": _scalarize(_process_plan_fp()),
        "pipeline_microbatches": cfg.get("pipeline_microbatches"),
        "moe_capacity_factor": cfg.get("moe_capacity_factor"),
        # int8 quantized inference (ISSUE 19): int8 params + packed
        # KV slab trace a DIFFERENT decode/forward program — flipping
        # the knob must orphan fp32 artifacts (and vice versa), never
        # load them stale.
        "inference_quant": cfg.get("inference_quant", "off"),
    }


def _process_plan_fp():
    from .parallel import plan as plan_mod

    p = plan_mod.process_plan()
    return None if p is None else p.fingerprint()


def _args_signature(args) -> Dict:
    """Abstract signature of a program-argument pytree: per-leaf
    shape/dtype plus the tree structure (two different arg nestings
    with identical leaves must not collide)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return ["arr", [int(d) for d in x.shape], str(x.dtype)]
        if isinstance(x, (bool, int, float, complex)):
            # Python scalars enter the jit as TRACED weak-typed values
            # — the program depends on their type, never their value.
            # Keying on the value (e.g. the optimizer step counter)
            # would make every resumed run a guaranteed miss and grow
            # the store one artifact per starting step.
            return ["pyscalar", type(x).__name__]
        return ["py", repr(x)]

    return {"tree": str(treedef), "leaves": [leaf(x) for x in leaves]}


def step_key(model, opt, kind: str, args,
             extras=None) -> Tuple[str, Dict]:
    """(sha256 hex key, human-readable parts) for one executable.

    `kind` distinguishes the step vs forward program family; `extras`
    carries per-subclass identity (the mesh layout for sharded steps,
    training flag + statics for forwards)."""
    import jax

    dev_kind = ""
    try:
        d = jax.devices()[0]
        dev_kind = f"{d.platform}/{getattr(d, 'device_kind', '')}"
    except Exception:
        pass
    from . import __version__ as singa_version

    parts = {
        "schema": SCHEMA,
        # framework version rides the key: op lowerings live in
        # singa_tpu, not the user model, so an upgrade must orphan the
        # store. (A dev-install edit without a version bump is the
        # residual risk — bump SCHEMA or GC the store for those.)
        "singa_tpu": singa_version,
        "kind": kind,
        "model": model.topology_fingerprint(),
        "model_class": type(model).__qualname__,
        "opt": _opt_fingerprint(opt),
        "knobs": knob_fingerprint(),
        "args": _args_signature(args),
        "jax": jax.__version__,
        "device_kind": dev_kind,
        "n_devices": jax.device_count(),
        "extras": _scalarize(extras),
    }
    blob = json.dumps(parts, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest(), parts


# ---------------------------------------------------------------------------
# Artifact store
# ---------------------------------------------------------------------------
ARTIFACT_SUFFIX = ".jexp"
MANIFEST_SUFFIX = ".jexp.json"


def _paths(key: str) -> Tuple[str, str]:
    base = os.path.join(_CONFIG["directory"], key[:32])
    return base + ARTIFACT_SUFFIX, base + MANIFEST_SUFFIX


def artifact_exists(key: str) -> bool:
    """Whether the store holds an artifact for `key` (existence only —
    `load` still digest-checks). The prewarm tool's `--dry-run` probe:
    answers "would this executable warm-start?" without deserializing,
    tracing, or touching the hit/miss counters."""
    return active() and os.path.exists(_paths(key)[0])


def load(key: str):
    """Deserialize the artifact for `key`, or None (miss / corrupt).

    The digest manifest is verified BEFORE deserialization (the
    `CheckpointManager` contract): a truncated or bit-rotted artifact
    is reported loudly, counted in `errors`, and the caller falls back
    to tracing — never a crash, never a silently wrong program."""
    path, man_path = _paths(key)
    if not os.path.exists(path):
        _STATS.misses += 1
        return None
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            blob = f.read()
        if os.path.exists(man_path):
            with open(man_path) as f:
                man = json.load(f)
            if len(blob) != man.get("size"):
                raise IOError(
                    f"size mismatch (manifest {man.get('size')}, on "
                    f"disk {len(blob)} — truncated write?)")
            if hashlib.sha256(blob).hexdigest() != man.get("sha256"):
                raise IOError("content digest mismatch (corrupt "
                              "artifact)")
        from jax import export as jexport

        exp = jexport.deserialize(blob)
    except Exception as e:
        _STATS.errors += 1
        _STATS.misses += 1
        print(f"singa_tpu: export cache artifact {path!r} failed to "
              f"load ({type(e).__name__}: {e}); falling back to "
              "tracing", file=sys.stderr)
        return None
    _STATS.hits += 1
    _STATS.load_s += time.perf_counter() - t0
    return exp


def export_and_save(key: str, parts: Dict, jitted, args):
    """Trace+lower `jitted` with `jax.export`, persist the artifact
    (atomic publish + digest manifest sidecar), and return the
    `Exported`. Returns None when the program cannot be exported
    (host callbacks etc.) — reported loudly; the caller keeps the
    plain jit. A save failure never fails the step."""
    from jax import export as jexport

    t0 = time.perf_counter()
    try:
        exp = jexport.export(jitted)(*args)
    except Exception as e:
        # the trace WAS paid before export rejected the program —
        # count it, or a callback-bearing model reports traces=0
        # while tracing every process (indistinguishable from warm)
        _STATS.traces += 1
        _STATS.trace_s += time.perf_counter() - t0
        _STATS.errors += 1
        print(f"singa_tpu: jax.export failed for {parts.get('kind')} "
              f"({type(e).__name__}: {e}); this executable will not "
              "warm-start", file=sys.stderr)
        return None
    _STATS.traces += 1
    _STATS.trace_s += time.perf_counter() - t0
    path, man_path = _paths(key)
    # per-process tmp names: fleet workers missing on the same key
    # concurrently must not interleave writes into one tmp file (the
    # os.replace publish itself is atomic either way)
    tmp_tag = f".tmp.{os.getpid()}"
    try:
        blob = exp.serialize()
        tmp = path + tmp_tag
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic publish
        man = {
            "sha256": hashlib.sha256(blob).hexdigest(),
            "size": len(blob),
            "created": time.time(),
            "key": key,
            # trimmed human-readable identity for the GC tool
            "meta": {
                "kind": parts.get("kind"),
                "model_class": parts.get("model_class"),
                "device_kind": parts.get("device_kind"),
                "n_devices": parts.get("n_devices"),
                "jax": parts.get("jax"),
                "knobs": parts.get("knobs"),
            },
        }
        mtmp = man_path + tmp_tag
        with open(mtmp, "w") as f:
            json.dump(man, f)
        os.replace(mtmp, man_path)
        _STATS.saves += 1
    except Exception as e:
        _STATS.errors += 1
        for victim in (path + tmp_tag, man_path + tmp_tag):
            try:
                os.remove(victim)
            except OSError:
                pass
        print(f"singa_tpu: export cache save failed for {path!r} "
              f"({type(e).__name__}: {e}); continuing untraced",
              file=sys.stderr)
    return exp


def count_trace(seconds: float) -> None:
    """A step/forward executable was traced WITHOUT the store (cache
    off): keeps `traces`/`trace_s` meaning 'tracing paid by this
    process' in both modes."""
    _STATS.traces += 1
    _STATS.trace_s += seconds


# ---------------------------------------------------------------------------
# Retrace-storm diagnosis (satellite)
# ---------------------------------------------------------------------------
def _fmt_sig(sig) -> str:
    return ", ".join(f"{dt}[{','.join(str(d) for d in shape)}]"
                     for shape, dt in sig)


def note_step_retrace(old_sig, new_sig) -> None:
    """A compiled train step saw a NEW abstract batch signature after
    warmup — i.e. XLA is about to retrace. One line, naming old vs
    new, so the bare `retraces` counter finally says WHICH shapes are
    churning (and the fix: bucket them)."""
    _STATS.step_retraces += 1
    print("singa_tpu: step retrace after warmup — abstract batch "
          f"shapes changed from ({_fmt_sig(old_sig)}) to "
          f"({_fmt_sig(new_sig)}); feed fixed/bucketed batch sizes on "
          "the training side (data.microbatches pads tails), or "
          "device.set_shape_buckets for serving forwards",
          file=sys.stderr)


# ---------------------------------------------------------------------------
# Pad-to-bucket dispatch helpers
# ---------------------------------------------------------------------------
def _batch_leader(arrays) -> Optional[int]:
    """Batch size of a dispatch: dim 0 of the FIRST array that has
    one (the framework-wide shape-inference convention; 0-d leaves —
    a scalar timestep, say — ride along unbucketed)."""
    for a in arrays:
        if getattr(a, "ndim", 0) >= 1:
            return int(a.shape[0])
    return None


def pad_batch(arrays, n_target: int):
    """Right-pad dim 0 of every array sharing the leading batch dim up
    to `n_target` by REPEATING the final sample — `data.microbatches`'
    pad idiom (real values, no NaN/denormal hazards). Arrays whose
    dim 0 differs from the batch leader ride through untouched."""
    import jax.numpy as jnp
    import numpy as np

    n = _batch_leader(arrays)
    if n is None or n == n_target:
        return list(arrays)
    out = []
    for a in arrays:
        if getattr(a, "ndim", 0) < 1 or int(a.shape[0]) != n:
            out.append(a)
            continue
        tail = a[-1:]
        reps = [n_target - n] + [1] * (a.ndim - 1)
        if isinstance(a, np.ndarray):
            out.append(np.concatenate([a, np.tile(tail, reps)]))
        else:
            out.append(jnp.concatenate([a, jnp.tile(tail, reps)]))
    return out


def batch_mask(n_real: int, n_target: int, dtype="float32"):
    """[n_target] mask: 1 for real rows, exact 0 for pad rows. With
    sum-based masked reductions the pad rows contribute exact zeros,
    so masked loss/metrics match the unpadded step bit-for-bit on
    exact arithmetic (tests/test_export_cache.py proves it)."""
    import numpy as np

    m = np.zeros((n_target,), dtype=dtype)
    m[:n_real] = 1
    return m


def pad_batch_to_bucket(arrays, policy: Optional[BucketPolicy] = None):
    """Bucket-pad a dispatch batch: returns (padded_arrays, info)
    where info = {n_real, n_bucket, seq_real, seq_bucket, seq_dim}
    (the slicing recipe for the reply). Raises `BucketOverflowError`
    (loudly) above the top bucket. Also buckets `policy.seq_dim` when
    configured (right-pad by repeating the final position —
    causal-safe only; see BucketPolicy); `seq_real/seq_bucket` report
    the FIRST seq-bearing input, which is what reply slicing keys on."""
    import jax.numpy as jnp
    import numpy as np

    pol = policy if policy is not None else bucket_policy()
    n = _batch_leader(arrays)
    info = {"n_real": n, "n_bucket": n,
            "seq_real": None, "seq_bucket": None,
            "seq_dim": None if pol is None else pol.seq_dim}
    if pol is None or n is None:
        return list(arrays), info
    target = pol.bucket_batch(n)
    info["n_bucket"] = target
    out = pad_batch(arrays, target)
    padded = target != n
    if pol.seq_dim is not None:
        d = pol.seq_dim
        seq_out = []
        for a in out:
            if getattr(a, "ndim", 0) > d:
                s = int(a.shape[d])
                st = pol.bucket_seq(s)
                if info["seq_real"] is None:
                    info["seq_real"], info["seq_bucket"] = s, st
                if st != s:
                    tail = jnp.take(a, jnp.asarray([s - 1]), axis=d) \
                        if not isinstance(a, np.ndarray) \
                        else np.take(a, [s - 1], axis=d)
                    reps = [1] * a.ndim
                    reps[d] = st - s
                    tile = (np.tile if isinstance(a, np.ndarray)
                            else jnp.tile)(tail, reps)
                    cat = (np.concatenate if isinstance(a, np.ndarray)
                           else jnp.concatenate)
                    a = cat([a, tile], axis=d)
                    padded = True
            seq_out.append(a)
        out = seq_out
    if padded:
        _STATS.bucket_pads += 1
    _STATS.note_bucket(tuple(
        (tuple(int(d) for d in getattr(a, "shape", ())),
         str(getattr(a, "dtype", ""))) for a in out))
    return out, info


def slice_bucket_out(out_tree, info):
    """Undo bucket padding on a reply pytree: leaves carrying the
    bucketed batch dim are cut back to `n_real`, and (when seq
    bucketing applied) leaves carrying the bucketed seq dim are cut
    back to `seq_real`. Batch-ness/seq-ness is inferred by SHAPE —
    the `_merge_accum_out` caveat: avoid bucket ceilings equal to
    unrelated output dims."""
    import jax

    n_real, n_bucket = info["n_real"], info["n_bucket"]
    s_real, s_bucket = info["seq_real"], info["seq_bucket"]
    d = info["seq_dim"]

    def leaf(a):
        if (n_bucket != n_real and getattr(a, "ndim", 0) >= 1
                and a.shape[0] == n_bucket):
            a = a[:n_real]
        if (s_bucket is not None and s_bucket != s_real
                and getattr(a, "ndim", 0) > d and a.shape[d] == s_bucket):
            idx = [slice(None)] * a.ndim
            idx[d] = slice(0, s_real)
            a = a[tuple(idx)]
        return a

    return jax.tree_util.tree_map(leaf, out_tree)


# ---------------------------------------------------------------------------
# Store inventory (tools/export_cache_gc.py)
# ---------------------------------------------------------------------------
def validate_artifact(path: str, deep: bool = True) -> Optional[str]:
    """None when `path` passes its manifest check (or is a
    manifest-less legacy artifact, validated by deserialization at
    load); otherwise the reason it is invalid. `deep=False` stops at
    the stat-only size check — listing a fleet store must not re-read
    and hash gigabytes of artifacts just to print names."""
    man_path = path + ".json"
    try:
        size = os.path.getsize(path)
    except OSError as e:
        return f"unreadable artifact: {e}"
    if not os.path.exists(man_path):
        return None
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return f"unreadable manifest: {e}"
    if size != man.get("size"):
        return (f"size mismatch (manifest {man.get('size')}, on disk "
                f"{size} — truncated write?)")
    if not deep:
        return None
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != man.get("sha256"):
        return "content digest mismatch (corrupt artifact)"
    return None


def list_artifacts(directory: Optional[str] = None,
                   deep: bool = True) -> List[Dict]:
    """Inventory rows for every artifact in the store: path, size,
    created, manifest meta, and the validation verdict (stat-only
    when `deep=False`; see `validate_artifact`)."""
    d = directory or _CONFIG["directory"]
    if d is None or not os.path.isdir(d):
        return []
    rows = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(ARTIFACT_SUFFIX):
            continue
        path = os.path.join(d, name)
        man_path = path + ".json"
        meta, created = {}, None
        if os.path.exists(man_path):
            try:
                with open(man_path) as f:
                    man = json.load(f)
                meta = man.get("meta", {})
                created = man.get("created")
            except (OSError, ValueError):
                pass
        rows.append({
            "path": path,
            "name": name,
            "size": os.path.getsize(path),
            "created": created,
            "meta": meta,
            "invalid": validate_artifact(path, deep=deep),
        })
    return rows
