"""Stateful layers over autograd ops.

Reference parity: `python/singa/layer.py` (SINGA 3.1+ API) — `Layer`
with lazy shape-inferred parameter creation on first call, hierarchical
name scoping, `get_params/set_params` (trainable) and
`get_states/set_states` (params + non-trainable state like BN running
stats), and the layer catalogue: Linear, Conv2d, SeparableConv2d,
BatchNorm2d, MaxPool2d, AvgPool2d, Dropout, Flatten, activation
layers, Cat, Embedding. RNN/LSTM/GRU live in `singa_tpu.rnn`.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from . import autograd, initializer, tensor as tensor_mod
from .ops import native
from .tensor import Tensor


class Layer:
    """Reference: `layer.Layer`.

    Parameters are created lazily in `initialize(*inputs)` on the first
    call, so input shapes are inferred — the reference's signature
    behavior. Sublayers and params are discovered via attribute
    assignment; hierarchical names are `parent.child.param`.
    """

    sep = "."

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__
        self._initialized = False
        self._parent = None

    # -- attribute registration -------------------------------------------
    def __setattr__(self, key, value):
        if isinstance(value, Layer):
            self.__dict__.setdefault("_sublayers", OrderedDict())[key] = value
        elif isinstance(value, Tensor) and getattr(value, "stores_grad", False):
            self.__dict__.setdefault("_params", OrderedDict())[key] = value
        object.__setattr__(self, key, value)

    @property
    def sublayers(self) -> "OrderedDict[str, Layer]":
        return self.__dict__.get("_sublayers", OrderedDict())

    @property
    def own_params(self) -> "OrderedDict[str, Tensor]":
        return self.__dict__.get("_params", OrderedDict())

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, *xs):
        """Create parameters from example inputs. Override in layers."""

    def forward(self, *xs):
        raise NotImplementedError

    def __call__(self, *xs):
        if not self._initialized:
            self.initialize(*xs)
            self._initialized = True
        return self.forward(*xs)

    def register_param(self, attr: str, t: Tensor):
        t.requires_grad = True
        t.stores_grad = True
        setattr(self, attr, t)
        return t

    def register_state(self, attr: str, t: Tensor):
        """Non-trainable state (e.g. BN running stats)."""
        t.requires_grad = False
        t.stores_grad = False
        self.__dict__.setdefault("_state_attrs", []).append(attr)
        object.__setattr__(self, attr, t)
        return t

    # -- param / state trees ----------------------------------------------
    def get_params(self, prefix: str = "") -> Dict[str, Tensor]:
        """Reference: `Layer.get_params` — name → trainable Tensor."""
        base = prefix + self.name if prefix == "" else prefix
        out: Dict[str, Tensor] = {}
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            p.name = full
            out[full] = p
        for lname, sub in self.sublayers.items():
            out.update(sub.get_params(base + self.sep + lname))
        return out

    def set_params(self, params: Dict[str, object], prefix: str = "") -> None:
        base = prefix + self.name if prefix == "" else prefix
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            if full in params:
                v = params[full]
                p.copy_from_numpy(np.asarray(v.to_numpy() if isinstance(v, Tensor) else v))
        for lname, sub in self.sublayers.items():
            sub.set_params(params, base + self.sep + lname)

    def get_states(self, prefix: str = "") -> Dict[str, Tensor]:
        """Reference: `Layer.get_states` — params + aux state.
        Single recursion: own params + own state attrs, then descend."""
        base = prefix + self.name if prefix == "" else prefix
        out: Dict[str, Tensor] = {}
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            p.name = full
            out[full] = p
        for attr in self.__dict__.get("_state_attrs", []):
            t = getattr(self, attr)
            full = base + self.sep + attr
            t.name = full
            out[full] = t
        for lname, sub in self.sublayers.items():
            out.update(sub.get_states(base + self.sep + lname))
        return out

    def set_states(self, states: Dict[str, object], prefix: str = "") -> None:
        base = prefix + self.name if prefix == "" else prefix
        self.set_params(states, prefix)
        for attr in self.__dict__.get("_state_attrs", []):
            full = base + self.sep + attr
            if full in states:
                v = states[full]
                getattr(self, attr).copy_from_numpy(
                    np.asarray(v.to_numpy() if isinstance(v, Tensor) else v)
                )
        for lname, sub in self.sublayers.items():
            sub.set_states(states, base + self.sep + lname)

    def state_tensors(self) -> List[Tensor]:
        """Non-param state tensors (ordered) — graph-mode capture set."""
        out = [getattr(self, a) for a in self.__dict__.get("_state_attrs", [])]
        for sub in self.sublayers.values():
            out.extend(sub.state_tensors())
        return out

    def param_tensors(self) -> List[Tensor]:
        out = list(self.own_params.values())
        for sub in self.sublayers.values():
            out.extend(sub.param_tensors())
        return out


# ---------------------------------------------------------------------------
# Concrete layers
# ---------------------------------------------------------------------------
class Linear(Layer):
    """Reference: `layer.Linear(num_output, bias=True)` — in features
    inferred on first call; y = x W + b with W (in, out)."""

    def __init__(self, num_output: int, bias: bool = True, name=None):
        super().__init__(name)
        self.num_output = num_output
        self.bias = bias

    def initialize(self, x: Tensor):
        in_features = x.shape[-1]
        w = Tensor((in_features, self.num_output), device=x.device)
        initializer.he_uniform(w)
        self.register_param("W", w)
        if self.bias:
            b = Tensor((self.num_output,), device=x.device)
            b.set_value(0.0)
            self.register_param("b", b)

    def forward(self, x: Tensor):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y


class Conv2d(Layer):
    """Reference: `layer.Conv2d(nb_kernels, kernel_size, stride, padding,
    dilation, group, bias)` — NCHW, in channels inferred."""

    def __init__(self, nb_kernels: int, kernel_size, stride=1, padding=0,
                 dilation=1, group=1, bias: bool = True, name=None):
        super().__init__(name)
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.group = group
        self.bias = bias

    def initialize(self, x: Tensor):
        in_channels = x.shape[1]
        self.handle = native.ConvHandle(
            in_channels, self.nb_kernels, self.kernel_size,
            stride=self.stride, padding=self.padding,
            dilation=self.dilation, groups=self.group, bias=self.bias,
        )
        kh, kw = self.handle.kernel_size
        w = Tensor((self.nb_kernels, in_channels // self.group, kh, kw),
                   device=x.device)
        initializer.he_uniform(w)
        self.register_param("W", w)
        if self.bias:
            b = Tensor((self.nb_kernels,), device=x.device)
            b.set_value(0.0)
            self.register_param("b", b)

    def forward(self, x: Tensor):
        if self.bias:
            return autograd.conv2d(self.handle, x, self.W, self.b)
        return autograd.conv2d(self.handle, x, self.W)


class SeparableConv2d(Layer):
    """Reference: `layer.SeparableConv2d` — depthwise + pointwise."""

    def __init__(self, nb_kernels: int, kernel_size, stride=1, padding=0,
                 bias: bool = False, name=None):
        super().__init__(name)
        self.depthwise = None  # built at init (needs in_channels)
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.bias = bias

    def initialize(self, x: Tensor):
        in_channels = x.shape[1]
        self.depthwise = Conv2d(in_channels, self.kernel_size,
                                stride=self.stride, padding=self.padding,
                                group=in_channels, bias=self.bias)
        self.pointwise = Conv2d(self.nb_kernels, 1, bias=self.bias)

    def forward(self, x: Tensor):
        return self.pointwise(self.depthwise(x))


class BatchNorm2d(Layer):
    """Reference: `layer.BatchNorm2d(momentum=0.9)`.

    NOTE on momentum semantics: SINGA passes `momentum` to cuDNN as
    exponentialAverageFactor, i.e. running = (1-m)*running + m*batch.
    """

    def __init__(self, momentum: float = 0.9, eps: float = 1e-5, name=None):
        super().__init__(name)
        self.momentum = momentum
        self.eps = eps

    def initialize(self, x: Tensor):
        c = x.shape[1]
        self.handle = native.BatchNormHandle(factor=self.momentum, eps=self.eps)
        scale = Tensor((c,), device=x.device)
        scale.set_value(1.0)
        self.register_param("scale", scale)
        bias = Tensor((c,), device=x.device)
        bias.set_value(0.0)
        self.register_param("bias", bias)
        rm = Tensor((c,), device=x.device)
        rm.set_value(0.0)
        self.register_state("running_mean", rm)
        rv = Tensor((c,), device=x.device)
        rv.set_value(1.0)
        self.register_state("running_var", rv)

    def forward(self, x: Tensor):
        op = autograd._BatchNorm2d(self.handle, self.running_mean,
                                   self.running_var)
        y = op(x, self.scale, self.bias)
        if autograd.training and op.new_running_mean is not None:
            # Rebind state (reference mutates in cuDNN); in graph mode
            # these become traced outputs captured by Model.compile.
            self.running_mean.data = op.new_running_mean
            self.running_var.data = op.new_running_var
        return y


class Pooling2d(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, is_max=True,
                 name=None):
        super().__init__(name)
        self.handle = native.PoolingHandle(kernel_size, stride=stride,
                                           padding=padding, is_max=is_max)

    def forward(self, x: Tensor):
        return autograd.pooling_2d(self.handle, x)


class MaxPool2d(Pooling2d):
    """Reference: `layer.MaxPool2d`."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(kernel_size, stride, padding, is_max=True, name=name)


class AvgPool2d(Pooling2d):
    """Reference: `layer.AvgPool2d`."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(kernel_size, stride, padding, is_max=False, name=name)


class Dropout(Layer):
    """Reference: `layer.Dropout(ratio)`."""

    def __init__(self, ratio: float = 0.5, name=None):
        super().__init__(name)
        self.ratio = ratio

    def forward(self, x: Tensor):
        # Key comes from the *input's* device each call (never cached:
        # params may migrate after a host-side init forward).
        key = (x.device.next_key()
               if autograd.training and self.ratio > 0.0 else None)
        return autograd.Dropout(self.ratio, rng_key=key)(x)


class Flatten(Layer):
    """Reference: `layer.Flatten(axis=1)`."""

    def __init__(self, axis: int = 1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, x: Tensor):
        return autograd.flatten(x, self.axis)


class ReLU(Layer):
    def forward(self, x):
        return autograd.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return autograd.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return autograd.tanh(x)


class Softmax(Layer):
    def __init__(self, axis: int = 1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, x):
        return autograd.softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01, name=None):
        super().__init__(name)
        self.a = negative_slope

    def forward(self, x):
        return autograd.LeakyRelu(self.a)(x)


class Gelu(Layer):
    def forward(self, x):
        return autograd.Gelu()(x)


class Cat(Layer):
    """Reference: `layer.Cat(axis)`."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, *xs):
        return autograd.cat(list(xs), self.axis)


class Embedding(Layer):
    """Reference: `layer.Embedding(input_dim, output_dim)` — lookup
    table, rows selected by int indices."""

    def __init__(self, input_dim: int, output_dim: int, name=None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def initialize(self, x: Tensor):
        w = Tensor((self.input_dim, self.output_dim), device=x.device)
        initializer.gaussian(w, 0.0, 0.05)
        self.register_param("W", w)

    def forward(self, x: Tensor):
        return autograd.embedding(self.W, x)


class LayerNorm(Layer):
    """LayerNorm over the trailing dim; params gamma/beta (lazy)."""

    def __init__(self, eps: float = 1e-5, name=None):
        super().__init__(name)
        self.eps = eps

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        g = Tensor((d,), device=x.device)
        b = Tensor((d,), device=x.device)
        initializer.constant(g, 1.0)
        initializer.constant(b, 0.0)
        self.register_param("gamma", g)
        self.register_param("beta", b)

    def forward(self, x: Tensor):
        return autograd.layer_norm(x, self.gamma, self.beta, self.eps)


class RMSNorm(Layer):
    """Root-mean-square norm (no reference equivalent; the modern-LM
    alternative to LayerNorm). Composed from primitive autograd ops so
    backward and ONNX export (Mul/ReduceMean/Add/Sqrt/Div) come from
    the existing mappings — XLA fuses the chain in graph mode."""

    def __init__(self, eps: float = 1e-6, name=None):
        super().__init__(name)
        self.eps = eps

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        g = Tensor((d,), device=x.device)
        initializer.constant(g, 1.0)
        self.register_param("gamma", g)

    def forward(self, x: Tensor):
        ms = autograd.ReduceMean(axes=[-1], keepdims=True)(
            autograd.mul(x, x))
        # eps passed as a python scalar per call (ops coerce it);
        # caching a constant TENSOR here is a trap — initialize/forward
        # may run inside a jit trace (Model.compile's init forward) and
        # a cached tracer-backed value would leak out of the trace
        rms = autograd.Sqrt()(autograd.add(ms, np.float32(self.eps)))
        return autograd.mul(autograd.div(x, rms), self.gamma)


class MultiHeadAttention(Layer):
    """Multi-head self-attention (no reference equivalent — SINGA's
    attention models arrive only via ONNX import). TPU-first: per-head
    projections stay one fused GEMM on the MXU; with `mesh` carrying a
    "seq" axis the score/softmax/value core runs as ring attention
    (sequence parallelism), and the q/k/v/o projections pick up tensor
    parallelism from the param sharding rules ("model" axis)."""

    def __init__(self, num_heads: int, causal: bool = True, mesh=None,
                 dropout: float = 0.0, name=None):
        super().__init__(name)
        self.num_heads = num_heads
        self.causal = causal
        self.mesh = mesh
        self.q_proj = Linear(0)  # lazy: sized to d_model on first call
        self.k_proj = Linear(0)
        self.v_proj = Linear(0)
        self.o_proj = Linear(0)
        self.drop = Dropout(dropout) if dropout else None

    def initialize(self, x: Tensor):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"d_model {d_model} not divisible by heads {self.num_heads}")
        for proj in (self.q_proj, self.k_proj, self.v_proj, self.o_proj):
            proj.num_output = d_model

    def forward(self, x: Tensor):
        B, S, E = x.shape
        H = self.num_heads
        D = E // H

        def split(t):  # [B,S,E] -> [B,H,S,D]
            t = autograd.reshape(t, (B, S, H, D))
            return autograd.transpose(t, (0, 2, 1, 3))

        q = split(self.q_proj(x))
        k = split(self.k_proj(x))
        v = split(self.v_proj(x))
        o = autograd.attention(q, k, v, causal=self.causal, mesh=self.mesh)
        o = autograd.transpose(o, (0, 2, 1, 3))
        o = autograd.reshape(o, (B, S, E))
        o = self.o_proj(o)
        return self.drop(o) if self.drop is not None else o


class Sequential(Layer):
    """Convenience container (reference builds these ad hoc)."""

    def __init__(self, *layers, name=None):
        super().__init__(name)
        for i, l in enumerate(layers):
            setattr(self, f"l{i}", l)
        self._seq = list(layers)

    def forward(self, x):
        for l in self._seq:
            x = l(x)
        return x


class MoE(Layer):
    """Trainable top-1 mixture-of-experts FFN (ISSUE 10; no reference
    equivalent — the GShard recipe of `parallel/moe.py` as a first-
    class layer). Params: replicated router `gate` (D, E) plus
    expert-stacked `w1`/`b1`/`w2`/`b2` whose leading expert dim the
    default sharding rules place on the mesh's "expert" axis, so a
    `ParallelPlan(expert=n)` shards expert compute across chips with
    GSPMD inserting the dispatch/combine all-to-alls.

    The auxiliary load-balancing loss of the LAST forward is exposed
    as `self.aux_loss` (a Tensor; add `aux_weight * layer.aux_loss`
    into the training loss — gradients flow through the router).
    BN-style state: `dropped_frac` holds an exponential moving average
    of the fraction of tokens dropped by expert-capacity overflow,
    updated only in training mode and captured as a program output in
    graph mode exactly like BatchNorm running stats.

    `capacity_factor=None` defers to the compile-time plan
    (`ParallelPlan.moe_capacity_factor`, default 1.25); the process
    knob `stats.moe_capacity_factor` — the autotuner's axis —
    overrides both at trace time."""

    def __init__(self, num_experts: int, d_ff: int,
                 capacity_factor: Optional[float] = None,
                 momentum: float = 0.9, mesh=None,
                 axis_name: str = "expert", name=None):
        super().__init__(name)
        self.num_experts = int(num_experts)
        self.d_ff = int(d_ff)
        self.capacity_factor = capacity_factor
        self.momentum = float(momentum)
        self.mesh = mesh
        self.axis_name = axis_name
        # which attrs the USER pinned at construction: plan wiring
        # only fills the others, and a RE-compile with a different
        # plan re-fills them (the set_grad_accum re-compile contract
        # — first-plan values must not stick)
        self._own_mesh = mesh is not None
        self._own_cf = capacity_factor is not None

    def _apply_plan(self, plan, mesh):
        if not self._own_mesh:
            self.mesh = mesh
        if not self._own_cf:
            self.capacity_factor = plan.moe_capacity_factor

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        e, f = self.num_experts, self.d_ff
        s1, s2 = 1.0 / np.sqrt(d), 1.0 / np.sqrt(f)
        gate = Tensor((d, e), device=x.device)
        initializer.gaussian(gate, 0.0, s1)
        self.register_param("gate", gate)
        w1 = Tensor((e, d, f), device=x.device)
        initializer.gaussian(w1, 0.0, s1)
        self.register_param("w1", w1)
        b1 = Tensor((e, f), device=x.device)
        b1.set_value(0.0)
        self.register_param("b1", b1)
        w2 = Tensor((e, f, d), device=x.device)
        initializer.gaussian(w2, 0.0, s2)
        self.register_param("w2", w2)
        b2 = Tensor((e, d), device=x.device)
        b2.set_value(0.0)
        self.register_param("b2", b2)
        df = Tensor((), device=x.device)
        df.set_value(0.0)
        self.register_state("dropped_frac", df)

    def forward(self, x: Tensor):
        import jax

        cf = self.capacity_factor if self.capacity_factor else 1.25
        y, aux, dropped = autograd.moe_ffn(
            x, self.gate, self.w1, self.b1, self.w2, self.b2,
            capacity_factor=cf, mesh=self.mesh,
            axis_name=self.axis_name)
        self.aux_loss = aux
        if autograd.training:
            # BN-style EMA rebind (raw arrays — state is non-grad; in
            # graph mode the new value is captured as a program
            # output, the BatchNorm contract)
            import jax.numpy as jnp

            m = self.momentum
            old = jnp.asarray(self.dropped_frac.data)
            new = ((1.0 - m) * old
                   + m * jnp.asarray(dropped.data).astype(old.dtype))
            self.dropped_frac.data = new
            from . import stats as stats_mod

            if not isinstance(dropped.data, jax.core.Tracer):
                stats_mod.note_moe_dropped(float(dropped.data))
        return y


class PipelineStack(Layer):
    """Homogeneous stack of pipeline stages (ISSUE 10; no reference
    equivalent). Holds P stages' parameters STACKED on a leading
    stage dim (registered as `stage_<leaf>` params, which the default
    sharding rules place on the mesh's "pipe" axis — chip i holds
    stage i), and runs `y = stage_{P-1}(...stage_0(x))`:

      * under a mesh whose "pipe" axis is >1 (a `ParallelPlan` with
        `pipe=n`): as a 1F1B (default) or GPipe schedule inside the
        compiled step (`parallel/pipeline.py`), microbatches threaded
        from the plan / the process knob;
      * otherwise (eager steps, single-device graphs, the lazy-init
        forward): as the bit-identical sequential composition.

    `stage_fn(params_dict, h) -> h` must be pure jax with output
    shape == input shape (homogeneous pipeline);
    `init_stage(key, x_shape) -> {leaf: array}` draws one stage's
    parameters from a PRNG key. `PipelineStack.mlp(...)` builds the
    canonical residual-GELU-MLP block stack."""

    def __init__(self, num_stages: int, stage_fn, init_stage, *,
                 mesh=None, axis_name: str = "pipe",
                 microbatches: Optional[int] = None,
                 schedule: Optional[str] = None, batch_axis=None,
                 name=None):
        super().__init__(name)
        self.num_stages = int(num_stages)
        if self.num_stages < 1:
            raise ValueError("PipelineStack needs num_stages >= 1")
        self._stage_fn = stage_fn
        self._init_stage = init_stage
        # stage_fn identity as a SCALAR config attr: the topology
        # fingerprint only hashes scalar layer config, and two stacks
        # with different stage math but identical param shapes must
        # never share an AOT artifact. Bytecode alone is NOT enough —
        # constants live in co_consts and factory-captured values in
        # closure cells (two `lambda p, h: h + c * (h @ p['W'])` with
        # different c share co_code) — so fold both in via the
        # export-cache scalarizer.
        import hashlib
        import json as _json

        from . import export_cache as _ec

        cells = []
        for c in getattr(stage_fn, "__closure__", None) or ():
            try:
                cells.append(_ec._scalarize(c.cell_contents, 1))
            except Exception:
                cells.append(type(c.cell_contents).__name__)
        self._stage_fn_id = hashlib.sha256(_json.dumps(
            [_ec._scalarize(stage_fn), cells], sort_keys=True,
            default=str).encode()).hexdigest()[:16]
        self.mesh = mesh
        self.axis_name = axis_name
        self.microbatches = microbatches
        self.schedule = schedule
        self.batch_axis = batch_axis
        # user-pinned ctor attrs (see MoE._apply_plan): plan wiring
        # fills the rest and RE-fills them on re-compile with a
        # different plan
        self._own_mesh = mesh is not None
        self._own_mb = microbatches is not None
        self._own_schedule = schedule is not None

    def _apply_plan(self, plan, mesh):
        if not self._own_mesh:
            self.mesh = mesh
        if not self._own_mb:
            self.microbatches = plan.pipeline_microbatches
        if not self._own_schedule:
            self.schedule = plan.pipeline_schedule

    @classmethod
    def mlp(cls, num_stages: int, d_ff: Optional[int] = None, **kw):
        """Residual pre-activation GELU MLP blocks:
        h + gelu(h W1 + b1) W2 + b2, with d_ff defaulting to 2*d."""
        import jax
        import jax.numpy as jnp

        def stage_fn(p, h):
            return h + jax.nn.gelu(h @ p["W1"] + p["b1"]) @ p["W2"] \
                + p["b2"]

        def init_stage(key, x_shape):
            d = int(x_shape[-1])
            f = d_ff or 2 * d
            k1, k2 = jax.random.split(key)
            s1, s2 = 1.0 / np.sqrt(d), 1.0 / np.sqrt(f)
            return {
                "W1": (jax.random.normal(k1, (d, f)) * s1
                       ).astype(jnp.float32),
                "b1": jnp.zeros((f,), jnp.float32),
                "W2": (jax.random.normal(k2, (f, d)) * s2
                       ).astype(jnp.float32),
                "b2": jnp.zeros((d,), jnp.float32),
            }

        return cls(num_stages, stage_fn, init_stage, **kw)

    def initialize(self, x: Tensor):
        import jax
        import jax.numpy as jnp

        dev = x.device
        # compile-time eval: init draws from CONCRETE keys even under
        # the eval_shape init forward (device.next_key's contract), so
        # no tracer can leak into the registered params
        with jax.ensure_compile_time_eval():
            per_stage = []
            for _ in range(self.num_stages):
                per_stage.append(
                    self._init_stage(dev.next_key(), tuple(x.shape)))
            names = sorted(per_stage[0])
            stacks = {nm: jnp.stack([jnp.asarray(st[nm])
                                     for st in per_stage])
                      for nm in names}
        for nm in names:
            t = tensor_mod.from_raw(stacks[nm], dev)
            self.register_param(f"stage_{nm}", t)
        self._leaf_names = tuple(names)

    def forward(self, x: Tensor):
        leaves = [getattr(self, f"stage_{nm}")
                  for nm in self._leaf_names]
        op = autograd.PipelineApply(
            self._stage_fn, self._leaf_names, self.num_stages,
            mesh=self.mesh, axis_name=self.axis_name,
            microbatches=self.microbatches,
            schedule=self.schedule or "1f1b",
            batch_axis=self.batch_axis)
        return op(x, *leaves)
