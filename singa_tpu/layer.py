"""Stateful layers over autograd ops.

Reference parity: `python/singa/layer.py` (SINGA 3.1+ API) — `Layer`
with lazy shape-inferred parameter creation on first call, hierarchical
name scoping, `get_params/set_params` (trainable) and
`get_states/set_states` (params + non-trainable state like BN running
stats), and the layer catalogue: Linear, Conv2d, SeparableConv2d,
BatchNorm2d, MaxPool2d, AvgPool2d, Dropout, Flatten, activation
layers, Cat, Embedding. RNN/LSTM/GRU live in `singa_tpu.rnn`.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from . import autograd, initializer, tensor as tensor_mod
from .ops import native
from .tensor import Tensor


class Layer:
    """Reference: `layer.Layer`.

    Parameters are created lazily in `initialize(*inputs)` on the first
    call, so input shapes are inferred — the reference's signature
    behavior. Sublayers and params are discovered via attribute
    assignment; hierarchical names are `parent.child.param`.
    """

    sep = "."

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__
        self._initialized = False
        self._parent = None

    # -- attribute registration -------------------------------------------
    def __setattr__(self, key, value):
        if isinstance(value, Layer):
            self.__dict__.setdefault("_sublayers", OrderedDict())[key] = value
        elif isinstance(value, Tensor) and getattr(value, "stores_grad", False):
            self.__dict__.setdefault("_params", OrderedDict())[key] = value
        object.__setattr__(self, key, value)

    @property
    def sublayers(self) -> "OrderedDict[str, Layer]":
        return self.__dict__.get("_sublayers", OrderedDict())

    @property
    def own_params(self) -> "OrderedDict[str, Tensor]":
        return self.__dict__.get("_params", OrderedDict())

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, *xs):
        """Create parameters from example inputs. Override in layers."""

    def forward(self, *xs):
        raise NotImplementedError

    def __call__(self, *xs):
        if not self._initialized:
            self.initialize(*xs)
            self._initialized = True
        return self.forward(*xs)

    def register_param(self, attr: str, t: Tensor):
        t.requires_grad = True
        t.stores_grad = True
        setattr(self, attr, t)
        return t

    def register_state(self, attr: str, t: Tensor):
        """Non-trainable state (e.g. BN running stats)."""
        t.requires_grad = False
        t.stores_grad = False
        self.__dict__.setdefault("_state_attrs", []).append(attr)
        object.__setattr__(self, attr, t)
        return t

    # -- param / state trees ----------------------------------------------
    def get_params(self, prefix: str = "") -> Dict[str, Tensor]:
        """Reference: `Layer.get_params` — name → trainable Tensor."""
        base = prefix + self.name if prefix == "" else prefix
        out: Dict[str, Tensor] = {}
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            p.name = full
            out[full] = p
        for lname, sub in self.sublayers.items():
            out.update(sub.get_params(base + self.sep + lname))
        return out

    def set_params(self, params: Dict[str, object], prefix: str = "") -> None:
        base = prefix + self.name if prefix == "" else prefix
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            if full in params:
                v = params[full]
                p.copy_from_numpy(np.asarray(v.to_numpy() if isinstance(v, Tensor) else v))
        for lname, sub in self.sublayers.items():
            sub.set_params(params, base + self.sep + lname)

    def get_states(self, prefix: str = "") -> Dict[str, Tensor]:
        """Reference: `Layer.get_states` — params + aux state.
        Single recursion: own params + own state attrs, then descend."""
        base = prefix + self.name if prefix == "" else prefix
        out: Dict[str, Tensor] = {}
        for pname, p in self.own_params.items():
            full = base + self.sep + pname
            p.name = full
            out[full] = p
        for attr in self.__dict__.get("_state_attrs", []):
            t = getattr(self, attr)
            full = base + self.sep + attr
            t.name = full
            out[full] = t
        for lname, sub in self.sublayers.items():
            out.update(sub.get_states(base + self.sep + lname))
        return out

    def set_states(self, states: Dict[str, object], prefix: str = "") -> None:
        base = prefix + self.name if prefix == "" else prefix
        self.set_params(states, prefix)
        for attr in self.__dict__.get("_state_attrs", []):
            full = base + self.sep + attr
            if full in states:
                v = states[full]
                getattr(self, attr).copy_from_numpy(
                    np.asarray(v.to_numpy() if isinstance(v, Tensor) else v)
                )
        for lname, sub in self.sublayers.items():
            sub.set_states(states, base + self.sep + lname)

    def state_tensors(self) -> List[Tensor]:
        """Non-param state tensors (ordered) — graph-mode capture set."""
        out = [getattr(self, a) for a in self.__dict__.get("_state_attrs", [])]
        for sub in self.sublayers.values():
            out.extend(sub.state_tensors())
        return out

    def param_tensors(self) -> List[Tensor]:
        out = list(self.own_params.values())
        for sub in self.sublayers.values():
            out.extend(sub.param_tensors())
        return out


# ---------------------------------------------------------------------------
# Concrete layers
# ---------------------------------------------------------------------------
class Linear(Layer):
    """Reference: `layer.Linear(num_output, bias=True)` — in features
    inferred on first call; y = x W + b with W (in, out)."""

    def __init__(self, num_output: int, bias: bool = True, name=None):
        super().__init__(name)
        self.num_output = num_output
        self.bias = bias

    def initialize(self, x: Tensor):
        in_features = x.shape[-1]
        w = Tensor((in_features, self.num_output), device=x.device)
        initializer.he_uniform(w)
        self.register_param("W", w)
        if self.bias:
            b = Tensor((self.num_output,), device=x.device)
            b.set_value(0.0)
            self.register_param("b", b)

    def forward(self, x: Tensor):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y


class Conv2d(Layer):
    """Reference: `layer.Conv2d(nb_kernels, kernel_size, stride, padding,
    dilation, group, bias)` — NCHW, in channels inferred."""

    def __init__(self, nb_kernels: int, kernel_size, stride=1, padding=0,
                 dilation=1, group=1, bias: bool = True, name=None):
        super().__init__(name)
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.group = group
        self.bias = bias

    def initialize(self, x: Tensor):
        in_channels = x.shape[1]
        self.handle = native.ConvHandle(
            in_channels, self.nb_kernels, self.kernel_size,
            stride=self.stride, padding=self.padding,
            dilation=self.dilation, groups=self.group, bias=self.bias,
        )
        kh, kw = self.handle.kernel_size
        w = Tensor((self.nb_kernels, in_channels // self.group, kh, kw),
                   device=x.device)
        initializer.he_uniform(w)
        self.register_param("W", w)
        if self.bias:
            b = Tensor((self.nb_kernels,), device=x.device)
            b.set_value(0.0)
            self.register_param("b", b)

    def forward(self, x: Tensor):
        if self.bias:
            return autograd.conv2d(self.handle, x, self.W, self.b)
        return autograd.conv2d(self.handle, x, self.W)


class SeparableConv2d(Layer):
    """Reference: `layer.SeparableConv2d` — depthwise + pointwise."""

    def __init__(self, nb_kernels: int, kernel_size, stride=1, padding=0,
                 bias: bool = False, name=None):
        super().__init__(name)
        self.depthwise = None  # built at init (needs in_channels)
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.bias = bias

    def initialize(self, x: Tensor):
        in_channels = x.shape[1]
        self.depthwise = Conv2d(in_channels, self.kernel_size,
                                stride=self.stride, padding=self.padding,
                                group=in_channels, bias=self.bias)
        self.pointwise = Conv2d(self.nb_kernels, 1, bias=self.bias)

    def forward(self, x: Tensor):
        return self.pointwise(self.depthwise(x))


class BatchNorm2d(Layer):
    """Reference: `layer.BatchNorm2d(momentum=0.9)`.

    NOTE on momentum semantics: SINGA passes `momentum` to cuDNN as
    exponentialAverageFactor, i.e. running = (1-m)*running + m*batch.
    """

    def __init__(self, momentum: float = 0.9, eps: float = 1e-5, name=None):
        super().__init__(name)
        self.momentum = momentum
        self.eps = eps

    def initialize(self, x: Tensor):
        c = x.shape[1]
        self.handle = native.BatchNormHandle(factor=self.momentum, eps=self.eps)
        scale = Tensor((c,), device=x.device)
        scale.set_value(1.0)
        self.register_param("scale", scale)
        bias = Tensor((c,), device=x.device)
        bias.set_value(0.0)
        self.register_param("bias", bias)
        rm = Tensor((c,), device=x.device)
        rm.set_value(0.0)
        self.register_state("running_mean", rm)
        rv = Tensor((c,), device=x.device)
        rv.set_value(1.0)
        self.register_state("running_var", rv)

    def forward(self, x: Tensor):
        op = autograd._BatchNorm2d(self.handle, self.running_mean,
                                   self.running_var)
        y = op(x, self.scale, self.bias)
        if autograd.training and op.new_running_mean is not None:
            # Rebind state (reference mutates in cuDNN); in graph mode
            # these become traced outputs captured by Model.compile.
            self.running_mean.data = op.new_running_mean
            self.running_var.data = op.new_running_var
        return y


class Pooling2d(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, is_max=True,
                 name=None):
        super().__init__(name)
        self.handle = native.PoolingHandle(kernel_size, stride=stride,
                                           padding=padding, is_max=is_max)

    def forward(self, x: Tensor):
        return autograd.pooling_2d(self.handle, x)


class MaxPool2d(Pooling2d):
    """Reference: `layer.MaxPool2d`."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(kernel_size, stride, padding, is_max=True, name=name)


class AvgPool2d(Pooling2d):
    """Reference: `layer.AvgPool2d`."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(kernel_size, stride, padding, is_max=False, name=name)


class Dropout(Layer):
    """Reference: `layer.Dropout(ratio)`."""

    def __init__(self, ratio: float = 0.5, name=None):
        super().__init__(name)
        self.ratio = ratio

    def forward(self, x: Tensor):
        # Key comes from the *input's* device each call (never cached:
        # params may migrate after a host-side init forward).
        key = (x.device.next_key()
               if autograd.training and self.ratio > 0.0 else None)
        return autograd.Dropout(self.ratio, rng_key=key)(x)


class Flatten(Layer):
    """Reference: `layer.Flatten(axis=1)`."""

    def __init__(self, axis: int = 1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, x: Tensor):
        return autograd.flatten(x, self.axis)


class ReLU(Layer):
    def forward(self, x):
        return autograd.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return autograd.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return autograd.tanh(x)


class Softmax(Layer):
    def __init__(self, axis: int = 1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, x):
        return autograd.softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01, name=None):
        super().__init__(name)
        self.a = negative_slope

    def forward(self, x):
        return autograd.LeakyRelu(self.a)(x)


class Gelu(Layer):
    def forward(self, x):
        return autograd.Gelu()(x)


class Cat(Layer):
    """Reference: `layer.Cat(axis)`."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, *xs):
        return autograd.cat(list(xs), self.axis)


class Embedding(Layer):
    """Reference: `layer.Embedding(input_dim, output_dim)` — lookup
    table, rows selected by int indices."""

    def __init__(self, input_dim: int, output_dim: int, name=None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def initialize(self, x: Tensor):
        w = Tensor((self.input_dim, self.output_dim), device=x.device)
        initializer.gaussian(w, 0.0, 0.05)
        self.register_param("W", w)

    def forward(self, x: Tensor):
        return autograd.embedding(self.W, x)


class LayerNorm(Layer):
    """LayerNorm over the trailing dim; params gamma/beta (lazy)."""

    def __init__(self, eps: float = 1e-5, name=None):
        super().__init__(name)
        self.eps = eps

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        g = Tensor((d,), device=x.device)
        b = Tensor((d,), device=x.device)
        initializer.constant(g, 1.0)
        initializer.constant(b, 0.0)
        self.register_param("gamma", g)
        self.register_param("beta", b)

    def forward(self, x: Tensor):
        return autograd.layer_norm(x, self.gamma, self.beta, self.eps)


class RMSNorm(Layer):
    """Root-mean-square norm (no reference equivalent; the modern-LM
    alternative to LayerNorm). Composed from primitive autograd ops so
    backward and ONNX export (Mul/ReduceMean/Add/Sqrt/Div) come from
    the existing mappings — XLA fuses the chain in graph mode."""

    def __init__(self, eps: float = 1e-6, name=None):
        super().__init__(name)
        self.eps = eps

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        g = Tensor((d,), device=x.device)
        initializer.constant(g, 1.0)
        self.register_param("gamma", g)

    def forward(self, x: Tensor):
        ms = autograd.ReduceMean(axes=[-1], keepdims=True)(
            autograd.mul(x, x))
        # eps passed as a python scalar per call (ops coerce it);
        # caching a constant TENSOR here is a trap — initialize/forward
        # may run inside a jit trace (Model.compile's init forward) and
        # a cached tracer-backed value would leak out of the trace
        rms = autograd.Sqrt()(autograd.add(ms, np.float32(self.eps)))
        return autograd.mul(autograd.div(x, rms), self.gamma)


class MultiHeadAttention(Layer):
    """Multi-head self-attention (no reference equivalent — SINGA's
    attention models arrive only via ONNX import). TPU-first: per-head
    projections stay one fused GEMM on the MXU; with `mesh` carrying a
    "seq" axis the score/softmax/value core runs as ring attention
    (sequence parallelism), and the q/k/v/o projections pick up tensor
    parallelism from the param sharding rules ("model" axis)."""

    def __init__(self, num_heads: int, causal: bool = True, mesh=None,
                 dropout: float = 0.0, name=None):
        super().__init__(name)
        self.num_heads = num_heads
        self.causal = causal
        self.mesh = mesh
        self.q_proj = Linear(0)  # lazy: sized to d_model on first call
        self.k_proj = Linear(0)
        self.v_proj = Linear(0)
        self.o_proj = Linear(0)
        self.drop = Dropout(dropout) if dropout else None

    def initialize(self, x: Tensor):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"d_model {d_model} not divisible by heads {self.num_heads}")
        for proj in (self.q_proj, self.k_proj, self.v_proj, self.o_proj):
            proj.num_output = d_model

    def forward(self, x: Tensor):
        B, S, E = x.shape
        H = self.num_heads
        D = E // H

        def split(t):  # [B,S,E] -> [B,H,S,D]
            t = autograd.reshape(t, (B, S, H, D))
            return autograd.transpose(t, (0, 2, 1, 3))

        q = split(self.q_proj(x))
        k = split(self.k_proj(x))
        v = split(self.v_proj(x))
        o = autograd.attention(q, k, v, causal=self.causal, mesh=self.mesh)
        o = autograd.transpose(o, (0, 2, 1, 3))
        o = autograd.reshape(o, (B, S, E))
        o = self.o_proj(o)
        return self.drop(o) if self.drop is not None else o


class Sequential(Layer):
    """Convenience container (reference builds these ad hoc)."""

    def __init__(self, *layers, name=None):
        super().__init__(name)
        for i, l in enumerate(layers):
            setattr(self, f"l{i}", l)
        self._seq = list(layers)

    def forward(self, x):
        for l in self._seq:
            x = l(x)
        return x
