"""Host-side data feeding utilities.

Reference parity: `python/singa/data.py` — `ImageBatchIter` (threaded
pre-fetch of (image, label) batches from a list file). TPU-native
redesign: a generic double-buffered `BatchIter` that overlaps host
augmentation with device steps (the reference uses a worker thread +
SafeQueue; so do we), plus `shard()` for per-host data sharding in
multi-controller SPMD runs.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class BatchIter:
    """Threaded prefetching batch iterator.

    `source` yields per-epoch iterables of (x, y) numpy batches (or any
    pytree of arrays). A worker thread keeps up to `prefetch` batches
    decoded ahead of the training loop — the host-side analogue of the
    reference's ImageBatchIter worker (python/singa/data.py).
    """

    def __init__(self, source: Callable[[], Iterable], prefetch: int = 2):
        self.source = source
        self.prefetch = prefetch

    def __iter__(self) -> Iterator:
        from . import trace as trace_mod

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        _END = object()
        closed = threading.Event()

        def worker():
            # Propagate pipeline failures to the consumer instead of
            # silently truncating the epoch; `closed` + put timeouts let
            # the worker exit when the consumer abandons the iterator
            # (a bounded q.put would otherwise block forever).
            def put(item):
                while not closed.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            try:
                for item in self.source():
                    if not put(item):
                        return
                put(_END)
            except BaseException as e:  # noqa: BLE001 — re-raised in consumer
                # Poison pill with the ORIGINAL exception + its
                # formatted worker traceback: the consumer re-raises
                # on its next __next__ instead of ending the epoch
                # silently, and the message still points at the
                # worker frame that actually failed.
                import traceback

                put((_END, e, traceback.format_exc()))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                # data_wait: how long the training loop stalls on the
                # host input pipeline (singa_tpu.trace span; the
                # per-step number MetricsLogger reports)
                with trace_mod.span("data_wait"):
                    item = q.get()
                if item is _END:
                    break
                if isinstance(item, tuple) and len(item) >= 2 \
                        and item[0] is _END:
                    e = item[1]
                    if len(item) == 3:
                        from .resilience import annotate_exception

                        annotate_exception(
                            e, "prefetch worker failed; original "
                               "traceback:\n" + item[2])
                    raise e
                yield item
        finally:
            closed.set()


def minibatches(x: np.ndarray, y: np.ndarray, batch_size: int,
                shuffle: bool = True, seed: Optional[int] = None,
                drop_last: bool = True) -> Iterator:
    """Yield (x_batch, y_batch) slices; the common epoch loop of the
    reference's examples (examples/cnn/train_cnn.py)."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    stop = n - batch_size + 1 if drop_last else n
    for i in range(0, stop, batch_size):
        j = idx[i:i + batch_size]
        yield x[j], y[j]


def microbatches(batch, n: int, pad: bool = False):
    """Split `batch` — one array, or any pytree of arrays / singa_tpu
    Tensors — into `n` equal microbatches along dim 0, as a list of n
    sub-pytrees with the original structure. The feeding-side
    companion of gradient accumulation (`device.set_grad_accum`): a
    `BatchIter` source can yield full effective batches and the train
    loop (or the compiled accum step itself, which does the same
    reshape in-program) never hand-slices.

    Every array leaf must share the same leading dimension, and it
    must divide by `n` — an indivisible batch raises a ValueError
    naming the offending size (silently dropping or duplicating
    samples would skew the gradient mean). Pass `pad=True` to instead
    right-pad every leaf by REPEATING its final sample up to the next
    multiple of n; padding changes the gradient weighting (the padded
    samples are real contributions), so it is opt-in and meant for
    tail batches where approximate weighting is acceptable.

    Tensor leaves are sliced on their device and wrapped back as
    Tensors; numpy/jax array leaves come back as views/slices of the
    same kind.
    """
    import jax

    from .tensor import Tensor

    if n < 1:
        raise ValueError(f"microbatches: n must be >= 1, got {n}")

    def is_tensor(x):
        return isinstance(x, Tensor)

    leaves, treedef = jax.tree_util.tree_flatten(
        batch, is_leaf=is_tensor)
    if not leaves:
        raise ValueError("microbatches: empty batch pytree")

    def leading_dim(t):
        # shape is an attribute read on Tensor/jax/numpy leaves —
        # never np.asarray, which would force a device-to-host
        # transfer just to measure an on-device array. (Python
        # lists/tuples never surface here: tree_flatten decomposes
        # them into their elements.)
        arr = t.data if is_tensor(t) else t
        shape = getattr(arr, "shape", None)
        if shape is not None and len(shape):
            return shape[0]
        return None  # scalar leaf: rides along whole

    dims_set = {d for d in map(leading_dim, leaves) if d is not None}
    if not dims_set:
        raise ValueError("microbatches: no leaf has a batch dimension")
    if len(dims_set) > 1:
        raise ValueError(
            f"microbatches: leaves disagree on batch size: "
            f"{sorted(dims_set)}")
    b = dims_set.pop()
    if b % n != 0:
        if not pad:
            raise ValueError(
                f"microbatches: batch size {b} is not divisible by "
                f"n={n}; pass pad=True to repeat-pad the tail, or "
                f"feed batches sized to a multiple of n")
        b_padded = ((b + n - 1) // n) * n
        extra = b_padded - b

        def pad_leaf(t):
            if leading_dim(t) is None:
                return t
            arr = t.data if is_tensor(t) else t
            tail = arr[-1:]
            reps = [extra] + [1] * (arr.ndim - 1)
            if isinstance(arr, np.ndarray):
                padded = np.concatenate([arr, np.tile(tail, reps)])
            else:
                import jax.numpy as jnp

                padded = jnp.concatenate(
                    [arr, jnp.tile(tail, reps)])
            if is_tensor(t):
                from . import tensor as tensor_mod

                return tensor_mod.from_raw(padded, t.device)
            return padded

        leaves = [pad_leaf(t) for t in leaves]
        b = b_padded
    mb = b // n

    def slice_leaf(t, k):
        if leading_dim(t) is None:
            return t  # scalar leaf rides along whole
        arr = t.data if is_tensor(t) else t
        piece = arr[k * mb:(k + 1) * mb]
        if is_tensor(t):
            from . import tensor as tensor_mod

            return tensor_mod.from_raw(piece, t.device)
        return piece

    return [jax.tree_util.tree_unflatten(
                treedef, [slice_leaf(t, k) for t in leaves])
            for k in range(n)]


def shard(x: np.ndarray, rank: int, world_size: int) -> np.ndarray:
    """Per-host shard of a dataset (multi-controller DP: each process
    feeds its slice; reference: global_rank-strided partition in
    examples/cnn/train_multiprocess.py's data split)."""
    n = (len(x) // world_size) * world_size
    return x[rank:n:world_size]


def prefetch_to_device(it: Iterable, device, size: int = 2) -> Iterator:
    """Move batches onto a device ahead of consumption so H2D transfer
    overlaps compute (the reference overlaps via pinned-memory copies
    on the CUDA copy stream; PJRT transfers are already async — this
    just issues them early)."""
    buf = []
    for item in it:
        import jax

        buf.append(jax.tree_util.tree_map(
            lambda a: jax.device_put(a, getattr(device, "jax_device",
                                                device)), item))
        if len(buf) > size:
            yield buf.pop(0)
    while buf:
        yield buf.pop(0)
