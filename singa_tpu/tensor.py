"""Tensor façade + free math functions.

Reference parity: this single module covers two reference layers —
  - `include/singa/core/tensor.h` / `src/core/tensor/tensor.cc`
    (`singa::Tensor`: shape/stride/Block*/Device*/DataType + ~120 free
    functions dispatched by `TYPE_LANG_SWITCH`), and
  - `python/singa/tensor.py` (the Python wrapper with operator sugar
    and the numpy bridge).

TPU-native redesign: there is no Block/stride machinery — a Tensor
wraps one immutable `jax.Array` (PJRT buffer) plus framework metadata
(device, requires_grad/stores_grad, creator link for autograd). All
math lowers to jnp/lax, i.e. per-op XLA programs cached by shape+dtype;
the reference's `tensor_math_cuda.h` kernel catalogue (KernelAdd,
KernelRelu, KernelRowMax, ...) maps 1:1 onto these functions. In-place
reference methods (`Tensor::Add` on self, `Axpy`) become rebinding of
`.data` — semantics preserved at the Python API level.

The functions here are *non-differentiable* primitives, exactly like
the reference's C++ free functions; differentiable ops live in
`singa_tpu.autograd` (the op registry).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .device import Device, get_default_device

# ---------------------------------------------------------------------------
# DataType registry. Reference: `singa::DataType` enum (proto/core.proto:
# kFloat32, kFloat16, kInt, kChar, kDouble) + AsType dispatch.
# ---------------------------------------------------------------------------
float32 = jnp.float32
float16 = jnp.float16
bfloat16 = jnp.bfloat16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_

# Reference enum names, kept for migration.
kFloat32 = float32
kFloat16 = float16
kBFloat16 = bfloat16
kInt = int32
kChar = jnp.int8  # reference kChar is signed char
kDouble = jnp.float64

_DTYPES = {
    "float32": float32,
    "float16": float16,
    "bfloat16": bfloat16,
    "int32": int32,
    "int64": int64,
    "uint8": uint8,
    "bool": bool_,
}


def _as_dtype(dt):
    if dt is None:
        return float32
    if isinstance(dt, str):
        return _DTYPES[dt]
    return dt


class Tensor:
    """N-d array on a Device.

    Reference: `singa::Tensor` + python `tensor.Tensor`. Attributes
    `requires_grad` / `stores_grad` and the `creator` link are consumed
    by `singa_tpu.autograd` exactly as in the reference's autograd
    (`python/singa/autograd.py`: creator-pointer DAG, no global tape).
    """

    __slots__ = (
        "data",
        "device",
        "requires_grad",
        "stores_grad",
        "creator",
        "creator_index",  # which output of `creator` this tensor is
        "name",
        # provenance flag set by autograd._dag_pairs: the wrapped array
        # is a fresh recorded-backward output nothing else references,
        # so the fused optimizer update may donate its buffer
        "_donatable",
    )

    def __init__(
        self,
        shape: Sequence[int] = (),
        device: Optional[Device] = None,
        dtype=float32,
        data=None,
        requires_grad: bool = True,
        stores_grad: bool = False,
        creator=None,
        name: Optional[str] = None,
    ):
        self.device = device or get_default_device()
        dtype = _as_dtype(dtype)
        if data is None:
            # Host-side numpy allocation placed with device_put: no
            # XLA program per shape, and the buffer stays concrete
            # even when constructed during a trace (lazy layer init
            # under the eval_shape compile pass).
            with jax.ensure_compile_time_eval():
                arr = jax.device_put(
                    np.zeros(tuple(shape), dtype=np.dtype(dtype)))
        elif isinstance(data, (np.ndarray, list, tuple, float, int)):
            arr = jnp.asarray(data, dtype=dtype)
        else:  # jax array — keep its dtype unless caller asked otherwise
            arr = data if data.dtype == dtype else data.astype(dtype)
        # Always commit the buffer to the requested device (no-op when
        # already resident there).
        self.data = self.device.put(arr)
        self.requires_grad = requires_grad
        self.stores_grad = stores_grad
        self.creator = creator
        self.creator_index = 0
        self.name = name

    # ---- metadata -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim

    def size(self) -> int:
        """Reference: `Tensor::Size` — element count."""
        return int(np.prod(self.shape)) if self.shape else 1

    def memsize(self) -> int:
        return self.size() * self.data.dtype.itemsize

    def is_empty(self) -> bool:
        return self.size() == 0

    def is_transpose(self) -> bool:
        """Reference keeps strides; XLA arrays are always dense/canonical."""
        return False

    # ---- conversion / movement -----------------------------------------
    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def copy_from_numpy(self, np_array: np.ndarray, offset: int = 0) -> None:
        """Reference: `Tensor::CopyDataFromHostPtr`. Rebinds the buffer."""
        assert offset == 0, "offset copies unsupported on immutable buffers"
        arr = np.ascontiguousarray(np_array)
        if arr.size != self.size():
            raise ValueError(
                f"size mismatch: tensor {self.shape} vs array {arr.shape}"
            )
        self.data = self.device.put(
            jnp.asarray(arr.reshape(self.shape), dtype=self.dtype)
        )

    def copy_data(self, t: "Tensor") -> None:
        """Reference: `Tensor::CopyData` — copy from another tensor."""
        self.data = jnp.asarray(t.data, dtype=self.dtype)

    def to_device(self, dev: Device) -> "Tensor":
        """Reference: `Tensor::ToDevice`. Returns self (mutating move)."""
        self.data = dev.put(self.data)
        self.device = dev
        return self

    def to_host(self) -> "Tensor":
        return self.to_device(get_default_device())

    def as_type(self, dtype) -> "Tensor":
        """Reference: `Tensor::AsType` (e.g. KernelCastFloat2Half)."""
        return _wrap(self.data.astype(_as_dtype(dtype)), self)

    def clone(self) -> "Tensor":
        """Reference: `Tensor::Clone` — deep copy (cheap: immutable buffer)."""
        t = Tensor.__new__(Tensor)
        t.data = self.data
        t.device = self.device
        t.requires_grad = self.requires_grad
        t.stores_grad = self.stores_grad
        t.creator = None
        t.creator_index = 0
        t.name = self.name
        return t

    # ---- shape ops ------------------------------------------------------
    def reshape(self, shape) -> "Tensor":
        return _wrap(jnp.reshape(self.data, tuple(shape)), self)

    def transpose(self, axes=None) -> "Tensor":
        """Reference: stride-based `Tensor::Transpose`; XLA materializes."""
        return _wrap(jnp.transpose(self.data, axes), self)

    @property
    def T(self) -> "Tensor":
        return self.transpose()

    def broadcast(self, shape) -> "Tensor":
        return _wrap(jnp.broadcast_to(self.data, tuple(shape)), self)

    def repeat(self, repeats, axis=None) -> "Tensor":
        """Reference: `Tensor::RepeatData`."""
        return _wrap(jnp.repeat(self.data, repeats, axis=axis), self)

    def squeeze(self, axis=None) -> "Tensor":
        return _wrap(jnp.squeeze(self.data, axis=axis), self)

    # ---- random fill ----------------------------------------------------
    # Reference: curand-backed `Uniform/Gaussian/Bernoulli` free fns;
    # here: counter-based threefry via the device key stream.
    # Fill methods compute values with HOST numpy (a Philox generator
    # seeded from the device's jax PRNG key, so `SetRandSeed`
    # determinism is preserved) and place the result with device_put
    # under `ensure_compile_time_eval`.  Two reasons: (a) values stay
    # CONCRETE even when the fill happens inside a trace — which is
    # what lets the zero-compile `Model._eval_shape_init_forward`
    # create real params while the init forward traces abstractly;
    # (b) no XLA programs get compiled per fill shape (ResNet-50 init
    # used to trigger 55 tiny backend compiles ≈ 14 s on first use).
    def _fill(self, arr) -> None:
        with jax.ensure_compile_time_eval():
            self.data = self.device.put(
                np.ascontiguousarray(
                    arr.astype(np.dtype(self.dtype), copy=False)))

    def _np_rng(self) -> np.random.Generator:
        kb = np.asarray(self.device.next_key()).ravel().view(np.uint32)
        return np.random.Generator(
            np.random.Philox((int(kb[0]) << 32) | int(kb[1])))

    def gaussian(self, mean: float = 0.0, std: float = 1.0) -> None:
        rng = self._np_rng()
        self._fill(rng.standard_normal(self.shape) * std + mean)

    def uniform(self, low: float = 0.0, high: float = 1.0) -> None:
        rng = self._np_rng()
        self._fill(rng.random(self.shape) * (high - low) + low)

    def bernoulli(self, p: float) -> None:
        rng = self._np_rng()
        self._fill((rng.random(self.shape) < p).astype(np.float32))

    def set_value(self, x) -> None:
        """Reference: `Tensor::SetValue` — fill with scalar."""
        with jax.ensure_compile_time_eval():
            self.data = self.device.put(
                np.full(self.shape, x, dtype=np.dtype(self.dtype)))

    # ---- python protocol -------------------------------------------------
    def __len__(self):
        if not self.shape:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name if hasattr(self.dtype, 'name') else self.dtype}, "
            f"device={self.device.lang})"
        )

    def __float__(self):
        assert self.size() == 1
        return float(self.data)

    def __int__(self):
        assert self.size() == 1
        return int(self.data)

    def item(self):
        return self.data.item()

    def __getitem__(self, idx):
        return _wrap(self.data[idx], self)

    # ---- operator sugar (non-differentiable, like reference tensor.py) ---
    def __add__(self, o):
        return _wrap(self.data + _raw(o), self)

    __radd__ = __add__

    def __sub__(self, o):
        return _wrap(self.data - _raw(o), self)

    def __rsub__(self, o):
        return _wrap(_raw(o) - self.data, self)

    def __mul__(self, o):
        return _wrap(self.data * _raw(o), self)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return _wrap(self.data / _raw(o), self)

    def __rtruediv__(self, o):
        return _wrap(_raw(o) / self.data, self)

    def __pow__(self, o):
        return _wrap(self.data ** _raw(o), self)

    def __neg__(self):
        return _wrap(-self.data, self)

    def __matmul__(self, o):
        return _wrap(jnp.matmul(self.data, _raw(o),
                                precision=get_matmul_precision()), self)

    def __lt__(self, o):
        return _wrap((self.data < _raw(o)).astype(float32), self)

    def __le__(self, o):
        return _wrap((self.data <= _raw(o)).astype(float32), self)

    def __gt__(self, o):
        return _wrap((self.data > _raw(o)).astype(float32), self)

    def __ge__(self, o):
        return _wrap((self.data >= _raw(o)).astype(float32), self)

    # In-place (reference mutates Blocks; here rebinds buffer).
    def __iadd__(self, o):
        self.data = self.data + _raw(o)
        return self

    def __isub__(self, o):
        self.data = self.data - _raw(o)
        return self

    def __imul__(self, o):
        self.data = self.data * _raw(o)
        return self

    def __itruediv__(self, o):
        self.data = self.data / _raw(o)
        return self


def _raw(x):
    return x.data if isinstance(x, Tensor) else x


def _wrap(arr, like: Tensor) -> Tensor:
    t = Tensor.__new__(Tensor)
    t.data = arr
    t.device = like.device
    t.requires_grad = False
    t.stores_grad = False
    t.creator = None
    t.creator_index = 0
    t.name = None
    return t


def _wrap_dev(arr, dev: Device) -> Tensor:
    t = Tensor.__new__(Tensor)
    t.data = arr
    t.device = dev
    t.requires_grad = False
    t.stores_grad = False
    t.creator = None
    t.creator_index = 0
    t.name = None
    return t


# ---------------------------------------------------------------------------
# Constructors. Reference: python tensor.py `from_numpy`, `zeros_like`, ...
# ---------------------------------------------------------------------------
def from_numpy(np_array, device: Optional[Device] = None) -> Tensor:
    np_array = np.asarray(np_array)
    dev = device or get_default_device()
    dtype = np_array.dtype
    if dtype == np.float64:
        dtype = np.float32
    if dtype == np.int64:
        dtype = np.int32
    arr = dev.put(jnp.asarray(np_array, dtype=dtype))
    return _wrap_dev(arr, dev)


def from_raw(arr, device: Optional[Device] = None) -> Tensor:
    """Wrap a raw jax array."""
    return _wrap_dev(arr, device or get_default_device())


def zeros(shape, device=None, dtype=float32) -> Tensor:
    dev = device or get_default_device()
    return _wrap_dev(dev.put(jnp.zeros(tuple(shape), _as_dtype(dtype))), dev)


def ones(shape, device=None, dtype=float32) -> Tensor:
    dev = device or get_default_device()
    return _wrap_dev(dev.put(jnp.ones(tuple(shape), _as_dtype(dtype))), dev)


def full(shape, value, device=None, dtype=float32) -> Tensor:
    dev = device or get_default_device()
    return _wrap_dev(dev.put(jnp.full(tuple(shape), value, _as_dtype(dtype))), dev)


def zeros_like(t: Tensor) -> Tensor:
    return _wrap(jnp.zeros_like(t.data), t)


def ones_like(t: Tensor) -> Tensor:
    return _wrap(jnp.ones_like(t.data), t)


def arange(start, stop=None, step=1, device=None, dtype=float32) -> Tensor:
    dev = device or get_default_device()
    return _wrap_dev(dev.put(jnp.arange(start, stop, step, _as_dtype(dtype))), dev)


def eye(n, device=None, dtype=float32) -> Tensor:
    dev = device or get_default_device()
    return _wrap_dev(dev.put(jnp.eye(n, dtype=_as_dtype(dtype))), dev)


def random(shape, device=None) -> Tensor:
    t = zeros(shape, device)
    t.uniform(0.0, 1.0)
    return t


def gaussian(shape, mean=0.0, std=1.0, device=None) -> Tensor:
    t = zeros(shape, device)
    t.gaussian(mean, std)
    return t


def uniform(low, high, shape, device=None) -> Tensor:
    t = zeros(shape, device)
    t.uniform(low, high)
    return t


def bernoulli(p, shape, device=None) -> Tensor:
    t = zeros(shape, device)
    t.bernoulli(p)
    return t


def to_numpy(t: Tensor) -> np.ndarray:
    return t.to_numpy()


def copy_data_to_from(dst: Tensor, src: Tensor, size=None) -> None:
    """Reference: `CopyDataToFrom` free fn."""
    dst.copy_data(src)


# ---------------------------------------------------------------------------
# Unary elementwise. Reference: EltwiseUnaryTensorFn macro expansion —
# Abs, Ceil, Exp, Log, ReLU, Sigmoid, Sign, Sqrt, Square, Tanh, ...
# (src/core/tensor/tensor.cc + tensor_math_cuda.h kernels).
# ---------------------------------------------------------------------------
def _unary(fn):
    def f(t: Tensor) -> Tensor:
        return _wrap(fn(t.data), t)

    return f


abs = _unary(jnp.abs)  # noqa: A001
ceil = _unary(jnp.ceil)
floor = _unary(jnp.floor)
round = _unary(jnp.round)  # noqa: A001
exp = _unary(jnp.exp)
log = _unary(jnp.log)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
sign = _unary(jnp.sign)
tanh = _unary(jnp.tanh)
sigmoid = _unary(jax.nn.sigmoid)
relu = _unary(jax.nn.relu)
sin = _unary(jnp.sin)
cos = _unary(jnp.cos)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
acos = _unary(jnp.arccos)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
cosh = _unary(jnp.cosh)
asinh = _unary(jnp.arcsinh)
acosh = _unary(jnp.arccosh)
atanh = _unary(jnp.arctanh)
erf = _unary(jax.scipy.special.erf)
reciprocal = _unary(lambda x: 1.0 / x)


def softmax(t: Tensor, axis: int = -1) -> Tensor:
    """Reference: `SoftMax` free fn (KernelSoftmax / cudnnSoftmaxForward)."""
    return _wrap(jax.nn.softmax(t.data, axis=axis), t)


def clip(t: Tensor, lo, hi) -> Tensor:
    return _wrap(jnp.clip(t.data, lo, hi), t)


# ---------------------------------------------------------------------------
# Binary elementwise with broadcast. Reference: Add/Sub/EltwiseMult/Div/Pow.
# ---------------------------------------------------------------------------
def add(a, b) -> Tensor:
    return _wrap(_raw(a) + _raw(b), a if isinstance(a, Tensor) else b)


def sub(a, b) -> Tensor:
    return _wrap(_raw(a) - _raw(b), a if isinstance(a, Tensor) else b)


def eltwise_mult(a, b) -> Tensor:
    return _wrap(_raw(a) * _raw(b), a if isinstance(a, Tensor) else b)


def div(a, b) -> Tensor:
    return _wrap(_raw(a) / _raw(b), a if isinstance(a, Tensor) else b)


def pow(a, b) -> Tensor:  # noqa: A001
    return _wrap(_raw(a) ** _raw(b), a if isinstance(a, Tensor) else b)


def maximum(a, b) -> Tensor:
    return _wrap(jnp.maximum(_raw(a), _raw(b)), a if isinstance(a, Tensor) else b)


def minimum(a, b) -> Tensor:
    return _wrap(jnp.minimum(_raw(a), _raw(b)), a if isinstance(a, Tensor) else b)


def axpy(alpha: float, x: Tensor, y: Tensor) -> Tensor:
    """Reference: `Axpy` (cublasSaxpy) — y += alpha * x, in place."""
    y.data = y.data + alpha * x.data
    return y


# ---------------------------------------------------------------------------
# Linear algebra. Reference: `Mult` → cublasSgemm/Sgemv; MXU territory.
#
# Precision policy: TPU MXU matmuls default to bf16 passes, which is a
# ~1% relative error vs the reference's fp32 cublasSgemm. The reference
# keeps fp32 math by default and gates half precision behind the
# `--precision` flag (train_cnn.py); we mirror that: "highest" (fp32,
# 3-pass) by default, switchable to "default" (bf16, fastest) for
# benchmark/throughput mode.
# ---------------------------------------------------------------------------
_matmul_precision = "highest"


def set_matmul_precision(p: str) -> None:
    """'highest' (fp32 parity, default) | 'high' | 'default' (bf16 fast)."""
    global _matmul_precision
    assert p in ("highest", "high", "default"), p
    _matmul_precision = p


def get_matmul_precision() -> str:
    return _matmul_precision


# ---------------------------------------------------------------------------
# Mixed-precision compute policy (TPU-native AMP). The reference gates
# half precision behind DistOpt's fp16 allreduce + `--precision`
# (train_cnn.py); the TPU-idiomatic equivalent is bf16 *compute* with
# fp32 master params: matmul/conv operands cast to bf16 at the op
# boundary (fp32 MXU accumulation), activations and their gradients
# flow bf16 (halving HBM traffic — the measured ResNet-50 bottleneck),
# while params, BN statistics, losses, and optimizer math stay fp32.
# ---------------------------------------------------------------------------
_compute_dtype = None  # None = policy off (full fp32 math)


def set_compute_dtype(dt) -> None:
    """Enable bf16 AMP: set_compute_dtype('bfloat16'); None disables."""
    global _compute_dtype
    _compute_dtype = jnp.dtype(dt) if dt is not None else None


def get_compute_dtype():
    return _compute_dtype


def amp_cast(*arrays):
    """Cast fp32 arrays to the compute dtype when the AMP policy is on
    (leaves integer / non-fp32 arrays and None untouched)."""
    if _compute_dtype is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = tuple(
        a.astype(_compute_dtype)
        if a is not None and hasattr(a, "dtype") and a.dtype == jnp.float32
        else a
        for a in arrays
    )
    return out if len(out) != 1 else out[0]


def mult(a: Tensor, b: Tensor) -> Tensor:
    """GEMM/GEMV. Reference: `Mult(const Tensor&, const Tensor&)`."""
    return _wrap(jnp.matmul(a.data, b.data, precision=_matmul_precision), a)


matmul = mult


def einsum(subscripts: str, *ts: Tensor) -> Tensor:
    return _wrap(jnp.einsum(subscripts, *[t.data for t in ts],
                            precision=get_matmul_precision()), ts[0])


def tensordot(a: Tensor, b: Tensor, axes=2) -> Tensor:
    return _wrap(jnp.tensordot(a.data, b.data, axes=axes,
                               precision=get_matmul_precision()), a)


# ---------------------------------------------------------------------------
# Reductions. Reference: Sum, SumRows/SumColumns, RowMax (KernelRowMax),
# Average.
# ---------------------------------------------------------------------------
def sum(t: Tensor, axis=None, keepdims=False) -> Tensor:  # noqa: A001
    return _wrap(jnp.sum(t.data, axis=axis, keepdims=keepdims), t)


def average(t: Tensor, axis=None, keepdims=False) -> Tensor:
    return _wrap(jnp.mean(t.data, axis=axis, keepdims=keepdims), t)


mean = average


def max(t: Tensor, axis=None, keepdims=False) -> Tensor:  # noqa: A001
    return _wrap(jnp.max(t.data, axis=axis, keepdims=keepdims), t)


def min(t: Tensor, axis=None, keepdims=False) -> Tensor:  # noqa: A001
    return _wrap(jnp.min(t.data, axis=axis, keepdims=keepdims), t)


def sum_rows(t: Tensor) -> Tensor:
    """Reference: `SumRows` — sum over axis 0 of a matrix."""
    return _wrap(jnp.sum(t.data, axis=0), t)


def sum_columns(t: Tensor) -> Tensor:
    """Reference: `SumColumns` — sum over axis 1 of a matrix."""
    return _wrap(jnp.sum(t.data, axis=1), t)


def row_max(t: Tensor) -> Tensor:
    """Reference: `RowMax` (KernelRowMax)."""
    return _wrap(jnp.max(t.data, axis=1), t)


def argmax(t: Tensor, axis=-1) -> Tensor:
    return _wrap(jnp.argmax(t.data, axis=axis).astype(int32), t)


def argmin(t: Tensor, axis=-1) -> Tensor:
    return _wrap(jnp.argmin(t.data, axis=axis).astype(int32), t)


# ---------------------------------------------------------------------------
# Row/column broadcast helpers. Reference: AddRow/AddColumn/MultRow/
# MultColumn/DivRow/DivColumn (tensor.cc).
# ---------------------------------------------------------------------------
def add_row(v: Tensor, m: Tensor) -> Tensor:
    """m[i,:] += v (v has shape (cols,))."""
    return _wrap(m.data + v.data[None, :], m)


def add_column(v: Tensor, m: Tensor) -> Tensor:
    """m[:,j] += v (v has shape (rows,))."""
    return _wrap(m.data + v.data[:, None], m)


def mult_row(v: Tensor, m: Tensor) -> Tensor:
    return _wrap(m.data * v.data[None, :], m)


def mult_column(v: Tensor, m: Tensor) -> Tensor:
    return _wrap(m.data * v.data[:, None], m)


def div_row(v: Tensor, m: Tensor) -> Tensor:
    return _wrap(m.data / v.data[None, :], m)


def div_column(v: Tensor, m: Tensor) -> Tensor:
    return _wrap(m.data / v.data[:, None], m)


# ---------------------------------------------------------------------------
# Shaping free fns. Reference: Reshape/Transpose/Concat(Rows|Columns)/
# Slice(Rows|Columns)/Stack/CopyRows.
# ---------------------------------------------------------------------------
def reshape(t: Tensor, shape) -> Tensor:
    return t.reshape(shape)


def transpose(t: Tensor, axes=None) -> Tensor:
    return t.transpose(axes)


def concatenate(ts: Sequence[Tensor], axis: int = 0) -> Tensor:
    return _wrap(jnp.concatenate([t.data for t in ts], axis=axis), ts[0])


concat = concatenate


def concat_rows(ts) -> Tensor:
    return concatenate(ts, axis=0)


def concat_columns(ts) -> Tensor:
    return concatenate(ts, axis=1)


def stack(ts: Sequence[Tensor], axis: int = 0) -> Tensor:
    return _wrap(jnp.stack([t.data for t in ts], axis=axis), ts[0])


def slice_rows(t: Tensor, start: int, end: int) -> Tensor:
    return _wrap(t.data[start:end], t)


def slice_columns(t: Tensor, start: int, end: int) -> Tensor:
    return _wrap(t.data[:, start:end], t)


def copy_rows(t: Tensor, start: int, end: int) -> Tensor:
    return slice_rows(t, start, end)


def split(t: Tensor, parts, axis: int = 0):
    return [_wrap(a, t) for a in jnp.split(t.data, parts, axis=axis)]


def tile(t: Tensor, reps) -> Tensor:
    return _wrap(jnp.tile(t.data, reps), t)


def gather(t: Tensor, indices, axis: int = 0) -> Tensor:
    idx = _raw(indices) if isinstance(indices, Tensor) else jnp.asarray(indices)
    return _wrap(jnp.take(t.data, idx.astype(jnp.int32), axis=axis), t)


def where(cond, a, b) -> Tensor:
    like = a if isinstance(a, Tensor) else (b if isinstance(b, Tensor) else cond)
    return _wrap(jnp.where(_raw(cond) != 0, _raw(a), _raw(b)), like)


def one_hot(indices, depth: int, device=None, dtype=float32) -> Tensor:
    idx = _raw(indices) if isinstance(indices, Tensor) else jnp.asarray(indices)
    dev = (
        indices.device
        if isinstance(indices, Tensor)
        else (device or get_default_device())
    )
    return _wrap_dev(
        jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=_as_dtype(dtype)), dev
    )


# ---------------------------------------------------------------------------
# Comparison free fns. Reference: LT/LE/GT/GE (tensor.cc) returning masks.
# ---------------------------------------------------------------------------
def lt(t: Tensor, x) -> Tensor:
    return t < x


def le(t: Tensor, x) -> Tensor:
    return t <= x


def gt(t: Tensor, x) -> Tensor:
    return t > x


def ge(t: Tensor, x) -> Tensor:
    return t >= x


# ---------------------------------------------------------------------------
# Loss helpers. Reference: ComputeCrossEntropy / SoftmaxCrossEntropyBwd
# (fused KernelSoftmaxCrossEntropy) — the differentiable version lives in
# autograd; these are the raw kernels.
# ---------------------------------------------------------------------------
def compute_cross_entropy(p: Tensor, t: Tensor) -> Tensor:
    """-sum(t * log(p)) per row; t may be one-hot or int labels."""
    pd = p.data
    td = t.data
    if td.ndim == pd.ndim - 1 or (td.ndim == pd.ndim and td.shape[-1] == 1):
        td = jax.nn.one_hot(td.reshape(td.shape[: pd.ndim - 1]).astype(jnp.int32),
                            pd.shape[-1], dtype=pd.dtype)
    eps = jnp.finfo(pd.dtype).tiny
    return _wrap(-jnp.sum(td * jnp.log(pd + eps), axis=-1), p)


def softmax_cross_entropy_bwd(p: Tensor, t: Tensor) -> Tensor:
    """Per-example grad of summed softmax-CE wrt logits: p - t.

    Callers computing the *mean* loss must scale by 1/batch themselves
    (the autograd SoftMaxCrossEntropy op does)."""
    pd, td = p.data, t.data
    if td.ndim == pd.ndim - 1 or (td.ndim == pd.ndim and td.shape[-1] == 1):
        td = jax.nn.one_hot(td.reshape(td.shape[: pd.ndim - 1]).astype(jnp.int32),
                            pd.shape[-1], dtype=pd.dtype)
    return _wrap(pd - td, p)
