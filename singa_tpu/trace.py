"""Step-timeline tracing, structured metrics logging, and device-
profiler hooks (ISSUE 5) — the standard instrumentation surface.

The reference's observability is a per-op wall-time table
(`Device::PrintTimeProfiling`); the TPU-native step is one opaque XLA
program, so op tables cannot say where a STEP spends its wall time —
waiting on the host input pipeline, dispatching the executable, or
blocked on the device. TVM (arXiv:1802.04799) makes the general point
(an optimizing stack is only as good as its cost visibility) and
µ-cuDNN (arXiv:1804.04806) the specific one (per-microbatch timing is
what justifies decomposition choices). Three pieces:

  - **Span tracer** — `span(name)` context managers, nestable and
    thread-safe, recorded into a bounded ring buffer. Disabled (the
    default) it is a strict no-op: `span()` returns a shared null
    context, nothing is recorded, nothing allocates. Spans are
    pre-wired through the whole step path (`data.BatchIter`
    data-wait, eager `train_one_batch` + the fused optimizer apply,
    `_JitStep` dispatch vs `block_until_ready` device-sync,
    `ShardedJitStep` shard placement, `run_resumable`
    checkpoint save/restore). Enable: `device.set_tracing(True)`.
    Export: `export_chrome_trace(path)` (Chrome trace-event /
    Perfetto JSON) or the per-step `format_summary()` table.
  - **MetricsLogger** — one schema-stable JSONL record per training
    step (step, loss, examples/sec, data-wait / dispatch /
    device-sync seconds, `cache_stats` counter deltas,
    resilience/accum counters, registered eval metrics), flushed
    record-atomically so a killed run (PR 3's `fit_resumable`)
    leaves a parseable log — `read_metrics` tolerates the one
    partial trailing line a kill mid-write can leave.
  - **Device profiler hook** — `profile_steps(n)` arms
    `jax.profiler` tracing for the next n step spans, so bench runs
    capture REAL device traces for steps k..k+n, not host proxies.

Counters surface in `cache_stats()["trace"]` and reset with
`reset_cache_stats()` (ring entries survive the reset — resetting
observability must not lose the timeline, the same contract as the
executable caches keeping their entries).
"""
from __future__ import annotations

import bisect
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from . import stats as stats_mod

__all__ = [
    "configure",
    "get_config",
    "enabled",
    "span",
    "record_span",
    "step_span",
    "records",
    "clear",
    "last_step_timings",
    "export_chrome_trace",
    "merge_chrome_traces",
    "aggregate_fleet",
    "span_summary",
    "format_summary",
    "profile_steps",
    "new_trace_id",
    "context",
    "current_trace",
    "current_span_id",
    "drain_shipped",
    "OffsetEstimator",
    "MetricsLogger",
    "read_metrics",
    "default_metrics_path",
]

# v2 (ISSUE 15): records additionally carry the writer `pid` and a
# `mono` perf_counter stamp paired with the wall-clock `time`, so
# multi-process logs are time-alignable offline. Additive only —
# `read_metrics` parses v1 and v2 records alike.
SCHEMA_VERSION = 2

_LOCK = threading.RLock()
_ENABLED = False
_RING: deque = deque(maxlen=16384)
_NEXT_ID = itertools.count(1)  # .__next__ is atomic in CPython
_TLS = threading.local()
_PROFILE: Optional[Dict] = None
_PROFILE_DIR = "/tmp/singa_tpu_profile"
_LAST_STEP: Optional[Dict] = None
# Cross-process span ship-back (ISSUE 15): spans carrying a trace
# context are ALSO buffered here when a capacity is armed
# (`configure(ship_capacity=n)`), for a transport to drain and ship to
# the parent process in bounded chunks. 0 = off (the default — only
# fleet workers arm it).
_SHIP: deque = deque()
_SHIP_CAP = 0


class _TraceStats:
    """cache_stats()["trace"]: spans recorded / dropped by the ring /
    step spans closed / chrome exports written / ship-back buffer
    accounting (buffered spans drained for cross-process shipping,
    drops when the bounded buffer overflows). reset() zeroes the
    counters; the ring itself is cleared only by `trace.clear()`."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.spans = 0
        self.dropped = 0
        self.steps = 0
        self.exports = 0
        self.shipped = 0
        self.ship_dropped = 0

    def snapshot(self) -> Dict:
        return {
            "enabled": _ENABLED,
            "spans": self.spans,
            "dropped": self.dropped,
            "steps": self.steps,
            "exports": self.exports,
            "shipped": self.shipped,
            "ship_dropped": self.ship_dropped,
            "ship_pending": len(_SHIP),
            "ring_size": len(_RING),
            "ring_capacity": _RING.maxlen,
        }


_STATS = _TraceStats()
stats_mod.register_cache("trace", _STATS)


# ---------------------------------------------------------------------------
# Config (user-facing setter: device.set_tracing — the reference's
# config surface, same pattern as every other knob).
# ---------------------------------------------------------------------------
def configure(enabled: Optional[bool] = None,
              ring_capacity: Optional[int] = None,
              profile_dir: Optional[str] = None,
              ship_capacity: Optional[int] = None) -> Dict:
    global _ENABLED, _RING, _PROFILE_DIR, _SHIP_CAP
    with _LOCK:
        if ring_capacity is not None:
            cap = int(ring_capacity)
            if cap < 1:
                raise ValueError("ring_capacity must be >= 1")
            if cap != _RING.maxlen:
                _RING = deque(_RING, maxlen=cap)
        if profile_dir is not None:
            _PROFILE_DIR = str(profile_dir)
        if ship_capacity is not None:
            cap = int(ship_capacity)
            if cap < 0:
                raise ValueError("ship_capacity must be >= 0 (0=off)")
            _SHIP_CAP = cap
            if cap == 0:
                _SHIP.clear()
        if enabled is not None:
            _ENABLED = bool(enabled)
    return get_config()


def get_config() -> Dict:
    return {"enabled": _ENABLED, "ring_capacity": _RING.maxlen,
            "profile_dir": _PROFILE_DIR, "ship_capacity": _SHIP_CAP}


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------
def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _ctx_stack() -> list:
    st = getattr(_TLS, "trace_stack", None)
    if st is None:
        st = _TLS.trace_stack = []
    return st


# ---------------------------------------------------------------------------
# Trace context (ISSUE 15): one request = one trace_id, born at the
# fleet router's submit and threaded through failover hops, client
# retries, and the process boundary, so every span a request touches —
# in any thread, in any PROCESS — carries the same id and the merged
# timeline can answer "where did this p99 request spend its time".
# ---------------------------------------------------------------------------
def new_trace_id() -> str:
    """A fresh 16-hex-char trace id, unique across processes."""
    import binascii

    return binascii.hexlify(os.urandom(8)).decode("ascii")


class _TraceCtx:
    """Thread-local trace-context frame: spans opened (or recorded via
    `record_span`) while it is active carry `trace` = the trace id;
    top-level spans additionally carry `remote_parent` — the span id
    in the ORIGINATING process under which they causally nest."""

    __slots__ = ("trace_id", "parent")

    def __init__(self, trace_id: str, parent):
        self.trace_id = trace_id
        self.parent = parent

    def __enter__(self):
        _ctx_stack().append(self)
        return self

    def __exit__(self, *exc):
        st = _ctx_stack()
        if st and st[-1] is self:
            st.pop()
        else:  # mismatched teardown: best-effort
            try:
                st.remove(self)
            except ValueError:
                pass
        return False


def context(trace_id: Optional[str] = None, parent=None):
    """Activate a trace context for the calling thread. With tracing
    disabled (or no id) this is the shared null context — strict
    no-op, nothing allocates, nothing propagates."""
    if not _ENABLED or trace_id is None:
        return _NULL
    return _TraceCtx(str(trace_id),
                     None if parent is None else int(parent))


def current_trace() -> Optional[Dict]:
    """The active trace context: {"trace_id", "parent"} or None."""
    st = getattr(_TLS, "trace_stack", None)
    if not st:
        return None
    c = st[-1]
    return {"trace_id": c.trace_id, "parent": c.parent}


def current_span_id() -> Optional[int]:
    """Id of the innermost OPEN span on this thread (the natural
    parent for work handed to another thread/process), or None."""
    st = getattr(_TLS, "stack", None)
    return st[-1].id if st else None


def _normalize_trace(trace):
    """(trace_id, parent) from a str / (id, parent) tuple / context
    dict / None."""
    if trace is None:
        return None, None
    if isinstance(trace, str):
        return trace, None
    if isinstance(trace, dict):
        return trace.get("trace_id"), trace.get("parent")
    tid = trace[0]
    parent = trace[1] if len(trace) > 1 else None
    return (None if tid is None else str(tid)), parent


def _ship(rec: Dict) -> None:
    """Buffer a trace-stamped span for cross-process ship-back.
    Bounded: overflow drops the OLDEST span and counts it — frames
    stay bounded, memory stays bounded, the loss is loud in
    `cache_stats()["trace"]["ship_dropped"]`. Only the fields the
    merged timeline needs are copied (wire bytes are request-path
    cost). Caller holds _LOCK."""
    if _SHIP_CAP <= 0:
        return
    if len(_SHIP) >= _SHIP_CAP:
        _SHIP.popleft()
        _STATS.ship_dropped += 1
    slim = {"name": rec["name"], "ts": rec["ts"], "dur": rec["dur"],
            "tid": rec["tid"], "trace": rec["trace"]}
    if rec.get("remote_parent") is not None:
        slim["remote_parent"] = rec["remote_parent"]
    if rec.get("args"):
        slim["args"] = rec["args"]
    _SHIP.append(slim)


def ship_backlog() -> tuple:
    """(buffered, capacity) of the ship-back buffer — transports use
    the pressure signal to decide whether to piggyback spans on a
    REPLY frame (request-path bytes, spent only when heartbeats are
    not keeping up) or leave them for the next heartbeat."""
    return len(_SHIP), _SHIP_CAP


def drain_shipped(max_n: int) -> List[Dict]:
    """Pop up to `max_n` buffered spans for shipping (oldest first).
    The per-call bound is the per-FRAME bound: a reply or heartbeat
    frame carries at most this many piggybacked spans, never an
    unbounded backlog."""
    out: List[Dict] = []
    with _LOCK:
        while _SHIP and len(out) < int(max_n):
            out.append(_SHIP.popleft())
        _STATS.shipped += len(out)
    return out


class _NullSpan:
    """The disabled-tracer span: a shared, stateless no-op context."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    # dur_s: the span's own measured duration, readable after exit —
    # a caller double-timing the same work (the online-SLO sketch
    # cross-validated against this very span) must feed the IDENTICAL
    # value, not a second clock read that diverges under load.
    __slots__ = ("name", "args", "id", "parent", "depth", "t0",
                 "dur_s")

    def __init__(self, name: str, args: Optional[Dict]):
        self.name = name
        self.args = args

    def __enter__(self):
        st = _stack()
        self.depth = len(st)
        self.parent = st[-1].id if st else None
        self.id = next(_NEXT_ID)
        st.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self.dur_s = t1 - self.t0
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        else:  # mismatched exit (generator teardown): best-effort
            try:
                st.remove(self)
            except ValueError:
                pass
        frame = getattr(_TLS, "step_frame", None)
        rec = {
            "name": self.name,
            # µs on the shared perf_counter clock (what Chrome "ts"
            # wants; absolute origin is irrelevant, only deltas are)
            "ts": self.t0 * 1e6,
            "dur": (t1 - self.t0) * 1e6,
            "tid": threading.get_ident(),
            "id": self.id,
            "parent": self.parent,
            "depth": self.depth,
            "step": frame["step"] if frame is not None else None,
        }
        if self.args:
            rec["args"] = self.args
        ctx = getattr(_TLS, "trace_stack", None)
        if ctx:
            c = ctx[-1]
            rec["trace"] = c.trace_id
            if self.parent is None and c.parent is not None:
                rec["remote_parent"] = c.parent
        with _LOCK:
            if not _ENABLED:
                return False  # disabled mid-span: drop silently
            if len(_RING) == _RING.maxlen:
                _STATS.dropped += 1
            _RING.append(rec)
            _STATS.spans += 1
            if "trace" in rec:
                _ship(rec)
            if frame is not None and self.name != "step":
                acc = frame["acc"]
                acc[self.name] = acc.get(self.name, 0.0) + (t1 - self.t0)
        return False


def span(name: str, **args):
    """Context manager timing one named host span. Nests (thread-local
    stack fixes depth/parent), records into the bounded ring on exit.
    Strict no-op while tracing is disabled: the shared `_NULL` context
    is returned, nothing is recorded or allocated."""
    if not _ENABLED:
        return _NULL
    return _Span(name, args or None)


def record_span(name: str, t0: float, t1: float, trace=None,
                **args) -> None:
    """Record an already-measured span from explicit `perf_counter`
    endpoints. The context-manager `span()` times work on ONE thread;
    a latency that starts on one thread and ends on another — a
    serving request's `queue_wait`, measured from the submitter's
    enqueue to the dispatcher's dequeue — can only be recorded after
    the fact. Same ring, same drop accounting, same strict no-op while
    tracing is disabled. Top-level by construction (no parent): the
    two endpoint threads have different span stacks, so nesting is
    undefined. `trace` attaches a trace context explicitly — a str
    trace id or a (trace_id, parent_span_id) pair — for spans whose
    owning request lives on another thread; None falls back to the
    calling thread's active context."""
    if not _ENABLED:
        return
    rec = {
        "name": name,
        "ts": t0 * 1e6,
        "dur": max(t1 - t0, 0.0) * 1e6,
        "tid": threading.get_ident(),
        "id": next(_NEXT_ID),
        "parent": None,
        "depth": 0,
        "step": None,
    }
    tid, parent = _normalize_trace(trace)
    if tid is None:
        ctx = current_trace()
        if ctx is not None:
            tid, parent = ctx["trace_id"], ctx["parent"]
    if tid is not None:
        rec["trace"] = tid
        if parent is not None:
            rec["remote_parent"] = parent
    if args:
        rec["args"] = args
    with _LOCK:
        if not _ENABLED:
            return
        if len(_RING) == _RING.maxlen:
            _STATS.dropped += 1
        _RING.append(rec)
        _STATS.spans += 1
        if "trace" in rec:
            _ship(rec)


class _StepCtx:
    """One training step: opens a "step" span, accumulates child span
    durations by name (the per-step data_wait / dispatch / device_sync
    decomposition `MetricsLogger` reads via `last_step_timings`), and
    drives the jax.profiler window armed by `profile_steps`."""

    __slots__ = ("step", "_span", "_frame", "_prev_frame", "_t0")

    def __init__(self, step):
        self.step = step

    def __enter__(self):
        _profile_step_started()
        if not _ENABLED:
            self._span = None
            return self
        self._prev_frame = getattr(_TLS, "step_frame", None)
        self._frame = {"step": self.step, "acc": {}}
        _TLS.step_frame = self._frame
        self._t0 = time.perf_counter()
        self._span = _Span("step", None)
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        global _LAST_STEP
        if self._span is not None:
            self._span.__exit__(*exc)
            wall = time.perf_counter() - self._t0
            _TLS.step_frame = self._prev_frame
            acc = self._frame["acc"]
            summary = {
                "step": self.step,
                "step_s": wall,
                "data_wait_s": acc.get("data_wait", 0.0),
                "dispatch_s": acc.get("dispatch", 0.0),
                "device_sync_s": acc.get("device_sync", 0.0),
            }
            with _LOCK:
                if _ENABLED:
                    _LAST_STEP = summary
                    _STATS.steps += 1
        _profile_step_finished()
        return False


def step_span(step=None):
    """Context manager for ONE training step. While tracing is enabled
    it opens a "step" span whose children (data_wait / dispatch /
    device_sync, emitted by the wired step path) become the per-step
    decomposition; it also ticks the `profile_steps` window either
    way. A strict no-op when tracing is off and no profile is armed."""
    if not _ENABLED and _PROFILE is None:
        return _NULL
    return _StepCtx(step)


def records() -> List[Dict]:
    """Snapshot of the span ring (oldest first)."""
    with _LOCK:
        return [dict(r) for r in _RING]


def clear() -> None:
    """Drop all recorded spans, the ship-back buffer, and the
    last-step summary (counters survive; use `reset_cache_stats()`
    for those)."""
    global _LAST_STEP
    with _LOCK:
        _RING.clear()
        _SHIP.clear()
        _LAST_STEP = None


def last_step_timings() -> Optional[Dict]:
    """The most recent closed step span's timing decomposition:
    {step, step_s, data_wait_s, dispatch_s, device_sync_s}. None until
    a step span closes with tracing enabled."""
    with _LOCK:
        return dict(_LAST_STEP) if _LAST_STEP else None


class OffsetEstimator:
    """Remote-monotonic-clock offset from request/reply round trips
    (ISSUE 18): `remote perf_counter + offset_us()/1e6 == local
    perf_counter`, within `uncertainty_us()`.

    Each `add(t_send, t_recv, t_remote)` is one round trip: the local
    send/receive stamps bracket the remote stamp, so the midpoint
    minus the remote stamp estimates the offset with error bounded by
    RTT/2 (classic NTP discipline). Over a real network the error is
    dominated by QUEUEING, not the path: a frame delayed in ONE
    direction biases its midpoint by delay/2 but also inflates its
    RTT — so the estimator keeps only the `k` smallest-RTT samples
    and reports the MEDIAN of their offsets. Clean round trips sink
    to the front and injected asymmetric delay is filtered out rather
    than averaged in; the median guards the case where every sample
    is jittered. `uncertainty_us()` is the best RTT's half-width —
    the bound the transport's offset-sanity pin checks against."""

    __slots__ = ("k", "_best")

    def __init__(self, k: int = 5):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = int(k)
        self._best: List[tuple] = []  # (rtt_s, offset_us), rtt-sorted

    def add(self, t_send: float, t_recv: float,
            t_remote: float) -> None:
        rtt = float(t_recv) - float(t_send)
        if rtt < 0.0:
            return  # caller bug or clock step; never poison the pool
        off = ((float(t_send) + float(t_recv)) / 2.0
               - float(t_remote)) * 1e6
        bisect.insort(self._best, (rtt, off))
        del self._best[self.k:]

    @property
    def n(self) -> int:
        return len(self._best)

    def rtt_s(self) -> Optional[float]:
        """Smallest RTT seen (seconds); None before any sample."""
        return self._best[0][0] if self._best else None

    def offset_us(self) -> Optional[float]:
        """Median offset over the k smallest-RTT samples (µs)."""
        if not self._best:
            return None
        offs = sorted(o for _, o in self._best)
        m = len(offs) // 2
        if len(offs) % 2:
            return offs[m]
        return (offs[m - 1] + offs[m]) / 2.0

    def uncertainty_us(self) -> Optional[float]:
        """Half the best RTT (µs) — the midpoint estimate's error
        bound; None before any sample."""
        if not self._best:
            return None
        return self._best[0][0] * 1e6 / 2.0


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------
def export_chrome_trace(path: str) -> str:
    """Write the span ring as Chrome trace-event JSON (the
    `chrome://tracing` / Perfetto `traceEvents` format: complete "X"
    events with µs ts/dur, nested by time containment per pid/tid).
    Atomic: written to a temp file and renamed into place."""
    pid = os.getpid()
    with _LOCK:
        recs = list(_RING)
    events = [_chrome_event(r, pid, 0.0) for r in recs]
    return _write_chrome(path, events)


def _chrome_event(r: Dict, default_pid: int, offset_us: float) -> Dict:
    """One ring record (or an already-chrome event) as a Chrome
    trace-event, with `offset_us` added to its timestamp — the clock
    alignment hook `merge_chrome_traces` applies per source."""
    ev = {"name": r["name"], "ph": r.get("ph", "X"),
          "cat": r.get("cat", "singa_tpu"),
          "ts": round(float(r["ts"]) + offset_us, 3),
          "dur": round(float(r.get("dur", 0.0)), 3),
          "pid": r.get("pid", default_pid), "tid": r.get("tid", 0)}
    args = dict(r.get("args") or {})
    for k in ("step", "trace", "remote_parent"):
        if r.get(k) is not None:
            args[k] = r[k]
    if args:
        ev["args"] = args
    return ev


def _write_chrome(path: str, events: List[Dict]) -> str:
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    with _LOCK:
        _STATS.exports += 1
    return path


def merge_chrome_traces(path: str, sources) -> str:
    """Merge span records from MANY processes into ONE Chrome/Perfetto
    timeline (ISSUE 15). Each source is a dict:

      records    span records (ring records, shipped worker spans, or
                 already-chrome events) — or
      path       a Chrome trace JSON file to fold in;
      pid        the pid to stamp on this source's events (default:
                 the records' own, else this process);
      offset_us  added to every timestamp — the per-worker
                 monotonic-clock offset the proc transport estimates
                 from the REQ→ACK handshake, so spans measured on N
                 different `perf_counter` origins land on ONE aligned
                 axis and a request's router/IPC/worker spans nest by
                 time containment across pids.

    Atomic write; returns `path`."""
    default_pid = os.getpid()
    events: List[Dict] = []
    for src in sources:
        recs = src.get("records")
        if recs is None and src.get("path"):
            try:
                with open(src["path"], "r", encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            recs = (data.get("traceEvents", [])
                    if isinstance(data, dict) else data)
        pid = src.get("pid")
        off = float(src.get("offset_us") or 0.0)
        for r in recs or []:
            ev = _chrome_event(r, default_pid, off)
            if pid is not None:
                ev["pid"] = pid
            events.append(ev)
    return _write_chrome(path, events)


def span_summary() -> Dict[str, Dict]:
    """Aggregate the ring by span name:
    name -> {count, total_ms, mean_ms, max_ms}."""
    out: Dict[str, Dict] = {}
    for r in records():
        s = out.setdefault(r["name"],
                           {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        d = r["dur"] / 1e3
        s["count"] += 1
        s["total_ms"] += d
        if d > s["max_ms"]:
            s["max_ms"] = d
    for s in out.values():
        s["mean_ms"] = round(s["total_ms"] / s["count"], 4)
        s["total_ms"] = round(s["total_ms"], 4)
        s["max_ms"] = round(s["max_ms"], 4)
    return out


def format_summary() -> str:
    """The per-step summary table: one row per span name with count,
    total/mean/max ms, and ms per step (total over the step spans in
    the ring) — the at-a-glance answer to "where does a step go"."""
    snap = span_summary()
    n_steps = max(snap.get("step", {}).get("count", 0), 1)
    lines = [f"trace summary ({n_steps} step span(s) in ring):",
             f"  {'span':<22} {'count':>7} {'total_ms':>10} "
             f"{'mean_ms':>9} {'max_ms':>9} {'ms/step':>9}"]
    for name, s in sorted(snap.items(), key=lambda kv: -kv[1]["total_ms"]):
        lines.append(
            f"  {name:<22} {s['count']:>7d} {s['total_ms']:>10.3f} "
            f"{s['mean_ms']:>9.3f} {s['max_ms']:>9.3f} "
            f"{s['total_ms'] / n_steps:>9.3f}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Device profiler hook: jax.profiler over a step window.
# ---------------------------------------------------------------------------
def profile_steps(n: int, logdir: Optional[str] = None) -> str:
    """Arm `jax.profiler.trace` for the NEXT `n` step spans: the trace
    starts when the next `step_span` opens and stops after n of them
    close, so bench runs capture real device traces for steps k..k+n
    (not host-side proxies) without bracketing warmup/compile noise.
    Returns the log directory (default: the `profile_dir` configured
    via `device.set_tracing`). One window at a time; re-arming
    replaces a not-yet-started window."""
    global _PROFILE
    n = int(n)
    if n < 1:
        raise ValueError(f"profile_steps: n must be >= 1, got {n}")
    with _LOCK:
        if _PROFILE is not None and _PROFILE["active"]:
            raise RuntimeError(
                "profile_steps: a profiler window is already running")
        _PROFILE = {"remaining": n,
                    "logdir": str(logdir or _PROFILE_DIR),
                    "active": False}
        return _PROFILE["logdir"]


def _profile_step_started() -> None:
    global _PROFILE
    with _LOCK:
        prof = _PROFILE
        if prof is None or prof["active"]:
            return
        prof["active"] = True
        logdir = prof["logdir"]
    try:
        import jax

        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
    except Exception as e:
        import sys

        print(f"singa_tpu: jax profiler start failed ({e!r}); "
              "profile window dropped", file=sys.stderr)
        with _LOCK:
            _PROFILE = None


def _profile_step_finished() -> None:
    global _PROFILE
    with _LOCK:
        prof = _PROFILE
        if prof is None or not prof["active"]:
            return
        prof["remaining"] -= 1
        if prof["remaining"] > 0:
            return
        _PROFILE = None
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:
        import sys

        print(f"singa_tpu: jax profiler stop failed ({e!r})",
              file=sys.stderr)


# ---------------------------------------------------------------------------
# Structured metrics log (JSONL, one record per train step).
# ---------------------------------------------------------------------------
def default_metrics_path(tag: str) -> str:
    """`$SINGA_TPU_METRICS_DIR/<tag>.jsonl` (default dir: ./metrics),
    created on demand — the directory `tools/tpu_watch.sh metrics`
    tails."""
    d = os.environ.get("SINGA_TPU_METRICS_DIR") or os.path.join(
        os.getcwd(), "metrics")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{tag}.jsonl")


def _json_default(v):
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


class MetricsLogger:
    """Append-only JSONL training log: ONE schema-stable record per
    training step, written as a single flush-per-record append so a
    SIGKILL mid-run leaves every completed record parseable
    (`read_metrics` skips the at-most-one partial trailing line).

    Record fields (always present, None when unknown): schema, time,
    pid, mono (wall/monotonic clock pair + writer pid — v2, ISSUE 15:
    multi-process fleet logs align offline), step, loss,
    examples_per_sec, step_s, data_wait_s, dispatch_s,
    device_sync_s (from the tracer's last closed step span when
    tracing is on), cache (per-cache COUNTER DELTAS since the previous
    record — retraces/step after warmup ≈ 0 is the healthy signal;
    live-state gauges, high-water marks, ratios and config knobs —
    the `_GAUGE_KEYS` set: slots_in_use, queue_depth, ring_size,
    size, occupancy, … — are passed through ABSOLUTE, since the
    delta of a gauge is signed noise: occupancy dropping between
    records would render as a negative "counter"),
    resilience + accum (absolute counters from `cache_stats()`),
    metrics (registered eval metrics — `Metric.register(logger)`),
    extra (caller keyword passthrough).

    `fsync=True` additionally fsyncs every record (survives OS crash,
    not just process kill) — off by default, it serializes the step
    loop on disk latency."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self._fsync = bool(fsync)
        self._lock = threading.Lock()
        self._prev_cache: Optional[Dict] = None
        self._metrics: Dict[str, object] = {}
        self.records_written = 0

    # -- metric registration (singa_tpu.metric.Metric.register) ----------
    def register_metric(self, name: str, metric) -> None:
        """Evaluate `metric` (anything with `.evaluate(outputs,
        labels) -> float`) into every record whose `log_step` call
        passes outputs/labels; the value lands under
        `record["metrics"][name]` — eval metrics in the same stream as
        the loss."""
        self._metrics[str(name)] = metric

    # Cache-snapshot fields that are NOT monotone counters: live-state
    # gauges (a shrinking gauge would delta negative), high-water
    # marks (reset() restarts them), derived ratios and config knobs
    # (whose deltas are meaningless). These pass through the delta
    # transform absolute.
    _GAUGE_KEYS = frozenset({
        # decode slot pool / LRU cache occupancy
        "slots", "slots_in_use", "size", "negative_size", "capacity",
        # serve queue live state, watermarks, derived ratios
        "queue_depth", "max_queue_depth", "effective_wait_ms",
        "coalesce_mean", "occupancy", "max_coalesce",
        # trace ring occupancy / config
        "ring_size", "ring_capacity", "ship_pending",
        # dag_route config knob
        "flops_per_op_threshold",
    })

    # -- record construction ----------------------------------------------
    def _cache_delta(self, snap: Dict) -> Dict:
        """Per-cache numeric-counter deltas vs the previous record
        (resilience/accum are reported absolute elsewhere; the
        `_GAUGE_KEYS` gauge/watermark/ratio fields are absolute
        too)."""
        cur: Dict = {}
        for name, s in snap.items():
            if name in ("resilience", "accum"):
                continue
            if isinstance(s, dict):
                cur[name] = {
                    k: v for k, v in s.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
            elif isinstance(s, (int, float)) and not isinstance(s, bool):
                cur[name] = s
        prev = self._prev_cache or {}
        out: Dict = {}
        for name, s in cur.items():
            if isinstance(s, dict):
                p = prev.get(name, {})
                if not isinstance(p, dict):
                    p = {}
                out[name] = {
                    k: (v if k in self._GAUGE_KEYS
                        else round(v - p.get(k, 0), 6)
                        if isinstance(v, float) else v - p.get(k, 0))
                    for k, v in s.items()}
            else:
                p = prev.get(name, 0)
                out[name] = s - (p if isinstance(p, (int, float)) else 0)
        self._prev_cache = cur
        return out

    def log_step(self, step, loss=None, examples=None, step_s=None,
                 outputs=None, labels=None, **extra) -> Dict:
        """Append the record for `step`. `loss` may be a Tensor /
        device scalar / float; `examples` is the batch's sample count
        (drives examples_per_sec); `step_s` overrides the tracer's
        step wall time (pass it when no step span wrapped the step).
        `outputs`/`labels` feed the registered eval metrics. Returns
        the record dict."""
        t = last_step_timings()
        if t is not None and t.get("step") not in (None, step):
            t = None  # stale frame from a different step: don't misattribute
        if step_s is None and t is not None:
            step_s = t["step_s"]
        snap = stats_mod.cache_stats()
        if loss is not None:
            loss = float(np.asarray(
                loss.to_numpy() if hasattr(loss, "to_numpy") else loss))
        if outputs is not None and labels is not None:
            mvals = {name: float(m.evaluate(outputs, labels))
                     for name, m in self._metrics.items()}
        else:
            mvals = {name: None for name in self._metrics}
        rec = {
            "schema": SCHEMA_VERSION,
            "time": round(time.time(), 3),
            # Writer pid + a monotonic stamp PAIRED with the wall
            # clock above (ISSUE 15): N per-process logs are
            # time-alignable offline — the (time, mono) pair in any
            # record recovers each process's perf_counter->wall
            # offset. Additive: read_metrics parses v1 records (no
            # pid/mono) and v2 alike.
            "pid": os.getpid(),
            "mono": round(time.perf_counter(), 6),
            "step": int(step),
            "loss": loss,
            "step_s": None if step_s is None else round(float(step_s), 6),
            "data_wait_s": round(t["data_wait_s"], 6) if t else None,
            "dispatch_s": round(t["dispatch_s"], 6) if t else None,
            "device_sync_s": round(t["device_sync_s"], 6) if t else None,
            "examples_per_sec": (
                round(float(examples) / float(step_s), 2)
                if examples and step_s else None),
            "cache": self._cache_delta(snap),
            "resilience": dict(snap.get("resilience", {})),
            "accum": dict(snap.get("accum", {})),
            "metrics": mvals,
            "extra": dict(extra),
        }
        self._write(rec)
        return rec

    def _write(self, rec: Dict) -> None:
        # one encode + one write + one flush per record: a kill lands
        # between records (or mid-way through at most the last line)
        data = (json.dumps(rec, sort_keys=True, default=_json_default)
                + "\n").encode("utf-8")
        with self._lock:
            self._f.write(data)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self.records_written += 1

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_metrics(path: str) -> List[Dict]:
    """Parse a metrics JSONL. Tolerant of the one artifact a killed
    run can leave — a partial trailing line — and of any interleaved
    garbage: non-JSON lines are skipped, never raised on."""
    out: List[Dict] = []
    try:
        f = open(path, "r", encoding="utf-8", errors="replace")
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Fleet telemetry aggregator (ISSUE 15): N per-replica/worker metrics
# JSONL streams + the merged span timeline -> ONE schema-stable fleet
# record. Consumed by `bench.py --stage fleet` (`latency_breakdown` /
# `trace` result blocks) and rendered by `tools/fleet_top.py`.
# ---------------------------------------------------------------------------
FLEET_AGGREGATE_SCHEMA = 1

# The per-segment latency decomposition: where a fleet request's time
# goes, one bucket per span name on the request path.
FLEET_SEGMENTS = ("queue_wait", "ipc", "dispatch", "reply", "route",
                  "failover", "submit", "batch_assemble",
                  # decode-tier SLO edges (ISSUE 16): time-to-first-
                  # token and time-per-output-token — additive;
                  # _segment_stats only emits names actually present
                  "ttft", "tpot")


def _segment_stats(spans) -> Dict[str, Dict]:
    by_name: Dict[str, List[float]] = {}
    for r in spans or []:
        name = r.get("name")
        if name in FLEET_SEGMENTS and r.get("dur") is not None:
            by_name.setdefault(name, []).append(float(r["dur"]) / 1e3)
    out: Dict[str, Dict] = {}
    for name, ms in by_name.items():
        arr = np.asarray(ms)
        out[name] = {
            "count": len(ms),
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
        }
    return out


def _load_chrome_events(chrome_trace: Optional[str]) -> List:
    """Events from a `merge_chrome_traces` output file (tolerant:
    unreadable/garbled files contribute nothing, matching
    `aggregate_fleet`'s behaviour)."""
    if not chrome_trace:
        return []
    try:
        with open(chrome_trace, "r", encoding="utf-8") as f:
            data = json.load(f)
        return list(data.get("traceEvents", [])
                    if isinstance(data, dict) else data)
    except (OSError, ValueError):
        return []


def fleet_segment_samples_ms(spans=None,
                             chrome_trace: Optional[str] = None
                             ) -> Dict[str, List[float]]:
    """Raw per-segment latency samples in ms, SORTED ascending — the
    post-hoc side of the ISSUE 20 online-SLO cross-validation.  Same
    span selection as `_segment_stats` (names in `FLEET_SEGMENTS`,
    `dur` present), but returning the samples themselves so a caller
    can apply the *sketch's* rank convention — ``rank = q*(n-1)``,
    value = first sample whose cumulative count exceeds ``rank``,
    i.e. ``sorted[floor(rank)]`` — instead of `np.percentile`'s
    interpolation, which disagrees at small n by more than the
    sketch's relative-error bound and would fail the gate spuriously."""
    all_spans = list(spans or [])
    all_spans.extend(_load_chrome_events(chrome_trace))
    out: Dict[str, List[float]] = {}
    for r in all_spans:
        name = r.get("name")
        if name in FLEET_SEGMENTS and r.get("dur") is not None:
            out.setdefault(name, []).append(float(r["dur"]) / 1e3)
    for v in out.values():
        v.sort()
    return out


def aggregate_fleet(paths=None, spans=None,
                    chrome_trace: Optional[str] = None) -> Dict:
    """Roll fleet telemetry into ONE schema-stable record:

      paths         metrics JSONL files (or directories globbed for
                    `*.jsonl`): the router's control-plane stream
                    (records whose `extra.event` is set) and the
                    per-replica/worker serving streams (per-dispatch
                    records) — both the `read_metrics` format, v1 or
                    v2 records alike.
      spans         span records (ring records or chrome events) for
                    the per-segment latency decomposition.
      chrome_trace  a merged Chrome trace file whose events join
                    `spans` (the `merge_chrome_traces` output).

    Returns {schema, kind, requests/replies/failed/rejected + routing
    counters, availability_pct, segments (queue/ipc/dispatch/reply/
    ttft/tpot/... p50/p99), events (the ejection/restart/kill
    state-transition timeline), workers (per-pid dispatch totals),
    decode (session terminals + migration/replay counts, ISSUE 17),
    replica_decode (per-replica session occupancy from the router's
    final record), trace_ids}. Every field is always present
    (None/empty when the inputs don't carry it) — the schema-stable
    contract every consumer pins on."""
    import glob as glob_mod

    files: List[str] = []
    for p in (paths or []):
        if os.path.isdir(p):
            files.extend(sorted(glob_mod.glob(os.path.join(p,
                                                           "*.jsonl"))))
        else:
            files.append(p)
    counters: Dict[str, int] = {}
    events: List[Dict] = []
    workers: Dict[str, Dict] = {}
    replica_decode: Dict[str, Dict] = {}
    for f in files:
        for rec in read_metrics(f):
            x = rec.get("extra") or {}
            if x.get("event"):
                # router control-plane record: counters are monotone
                # within a run — keep the max seen
                for k in ("fleet_requests", "fleet_replies",
                          "fleet_failed", "routed", "failovers",
                          "refused", "rejected", "ejections",
                          "rejoins", "restarts", "kills_injected",
                          "decode_requests", "decode_replies",
                          "decode_failed", "decode_migrations",
                          "decode_replays"):
                    v = x.get(k)
                    if isinstance(v, (int, float)):
                        counters[k] = max(counters.get(k, 0), int(v))
                # per-replica decode occupancy (ISSUE 17): the router
                # attaches a snapshot to its final "stop" record —
                # last writer wins (the freshest view of each replica)
                rd = x.get("replica_decode")
                if isinstance(rd, dict):
                    replica_decode.update(rd)
                if x["event"] == "transition":
                    events.append({
                        "t": rec.get("time"),
                        "replica": x.get("replica"),
                        "to_state": x.get("to_state"),
                        "reason": x.get("reason"),
                    })
            elif x.get("bucket") is not None:
                # per-dispatch serving record (engine or worker side)
                key = str(rec.get("pid") or os.path.basename(f))
                w = workers.setdefault(key, {
                    "dispatches": 0, "rows": 0, "expired": 0,
                    "shed": 0, "retries": 0, "failed": 0})
                w["dispatches"] += 1
                w["rows"] += int(x.get("rows") or 0)
                for k in ("expired", "shed", "retries", "failed"):
                    v = x.get(k)
                    if isinstance(v, (int, float)):
                        w[k] = max(w[k], int(v))  # cumulative in-stream
    all_spans = list(spans or [])
    all_spans.extend(_load_chrome_events(chrome_trace))
    trace_ids = set()
    for r in all_spans:
        t = r.get("trace") or (r.get("args") or {}).get("trace")
        if t:
            trace_ids.add(t)
    req = counters.get("fleet_requests")
    rep = counters.get("fleet_replies")
    avail = (round(100.0 * rep / req, 2)
             if req and rep is not None else None)
    return {
        "schema": FLEET_AGGREGATE_SCHEMA,
        "kind": "fleet_aggregate",
        "requests": req,
        "replies": rep,
        "failed": counters.get("fleet_failed"),
        "rejected": counters.get("rejected"),
        "routed": counters.get("routed"),
        "failovers": counters.get("failovers"),
        "refused": counters.get("refused"),
        "ejections": counters.get("ejections"),
        "restarts": counters.get("restarts"),
        "kills": counters.get("kills_injected"),
        "availability_pct": avail,
        "segments": _segment_stats(all_spans),
        "events": events,
        "workers": workers,
        # decode tier (ISSUE 17) — additive, schema-stable: always
        # present, None/empty when the inputs carry no decode traffic
        "decode": {
            "requests": counters.get("decode_requests"),
            "replies": counters.get("decode_replies"),
            "failed": counters.get("decode_failed"),
            "migrations": counters.get("decode_migrations"),
            "replays": counters.get("decode_replays"),
        },
        "replica_decode": replica_decode,
        "trace_ids": len(trace_ids),
        "span_count": len(all_spans),
    }
