"""Graph-mode per-op profiling: XLA HLO cost breakdown.

Reference parity: the reference times every graph node with cudaEvent
pairs inside `Graph::Run` and prints a per-op table via
`Device::PrintTimeProfiling` (src/core/scheduler/scheduler.cc,
SURVEY.md §5). In the TPU design the whole training step is ONE fused
XLA program, so "per-op kernel times" do not exist post-fusion; the
honest equivalent is:

  * measured wall time of the compiled step (recorded by `_JitStep`
    into the device's op-time table), plus
  * a per-HLO-instruction cost breakdown of the optimized program —
    FLOPs computed analytically from dot/convolution dimension numbers,
    bytes from operand/result shapes — with each top-level instruction
    attributed back to the framework op that produced it via the
    `op_name` metadata that `autograd.Operator.__call__` stamps with
    `jax.named_scope`.

Estimated per-region time = (region FLOPs / program FLOPs) x measured
step time; the table is explicit that these are cost-model estimates,
not per-kernel measurements.

No TensorFlow/profiler-plugin dependency: this parses the HLO text
that PJRT already returns (`compiled.as_text()`).
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%name = f32[2,3]{1,0} opcode(...)` (also matches tuple-typed results
# loosely; those get shape=None).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<shape>[0-9,]*)\]\S*\s+"
    r"(?P<opcode>[\w\-]+)\(")
_TUPLE_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*\("
    r".*?\)\s+(?P<opcode>[\w\-]+)\(")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*"
                      r"(?:\([^)]*\))?\s*->.*\{\s*$")
_OPERANDS_RE = re.compile(r"\(([^)]*)\)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIMLABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")


def _shape_of(type_str: str):
    m = re.match(r"([a-z][a-z0-9]*)\[([0-9,]*)\]", type_str)
    if not m:
        return None
    dims = [int(d) for d in m.group(2).split(",") if d] if m.group(2) else []
    return m.group(1), dims


def _numel(dims: List[int]) -> int:
    return int(math.prod(dims)) if dims else 1


class _Instr:
    __slots__ = ("name", "dtype", "dims", "opcode", "line")

    def __init__(self, name, dtype, dims, opcode, line):
        self.name, self.dtype, self.dims = name, dtype, dims
        self.opcode, self.line = opcode, line


def _parse_computations(hlo_text: str) -> Dict[str, List[_Instr]]:
    """Split module text into computations -> instruction lists."""
    comps: Dict[str, List[_Instr]] = {}
    current: Optional[str] = None
    for raw in hlo_text.splitlines():
        line = raw.rstrip()
        if current is None:
            m = _COMP_RE.match(line.strip())
            if m and line.rstrip().endswith("{"):
                current = m.group("name")
                comps[current] = []
            continue
        if line.strip() == "}":
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m:
            dims = ([int(d) for d in m.group("shape").split(",") if d]
                    if m.group("shape") else [])
            comps[current].append(_Instr(
                m.group("name"), m.group("dtype"), dims,
                m.group("opcode"), line))
            continue
        m = _TUPLE_INSTR_RE.match(line)
        if m:
            comps[current].append(_Instr(
                m.group("name"), None, None, m.group("opcode"), line))
    return comps


def _instr_flops(ins: _Instr, shapes: Dict[str, tuple]) -> float:
    """Analytic FLOPs for one instruction (0 for data movement)."""
    op = ins.opcode
    if op in ("parameter", "constant", "tuple", "get-tuple-element",
              "bitcast", "copy", "reshape", "transpose", "broadcast",
              "slice", "concatenate", "gather", "scatter", "pad",
              "dynamic-slice", "dynamic-update-slice", "iota",
              "convert", "reverse", "copy-start", "copy-done",
              "all-gather", "all-reduce", "reduce-scatter",
              "collective-permute", "partition-id", "replica-id"):
        return 0.0
    out_n = _numel(ins.dims) if ins.dims is not None else 0
    if op == "dot":
        m = _OPERANDS_RE.search(ins.line)
        c = _CONTRACT_RE.search(ins.line)
        if m and c:
            ops = [o.strip().lstrip("%") for o in m.group(1).split(",")]
            lhs = shapes.get(ops[0].split(" ")[0]) if ops else None
            if lhs:
                cdims = [int(d) for d in c.group(1).split(",") if d]
                k = _numel([lhs[1][d] for d in cdims if d < len(lhs[1])])
                return 2.0 * out_n * k
        return 2.0 * out_n  # fallback
    if op == "convolution":
        m = _OPERANDS_RE.search(ins.line)
        dl = _DIMLABELS_RE.search(ins.line)
        if m and dl:
            ops = [o.strip().lstrip("%") for o in m.group(1).split(",")]
            rhs = shapes.get(ops[1].split(" ")[0]) if len(ops) > 1 else None
            if rhs:
                o_pos = dl.group(2).index("o")
                rhs_n = _numel(rhs[1])
                o_size = rhs[1][o_pos] if o_pos < len(rhs[1]) else 1
                return 2.0 * out_n * rhs_n / max(o_size, 1)
        return 2.0 * out_n
    if op in ("exponential", "log", "tanh", "logistic", "power", "rsqrt",
              "sqrt", "sine", "cosine", "erf", "atan2", "expm1",
              "log-plus-one", "cbrt"):
        return 8.0 * out_n  # transcendental: several flops each
    if op == "reduce":
        # ~1 flop per reduced input element; approximate via operand.
        m = _OPERANDS_RE.search(ins.line)
        if m:
            ops = [o.strip().lstrip("%") for o in m.group(1).split(",")]
            src = shapes.get(ops[0].split(" ")[0]) if ops else None
            if src:
                return float(_numel(src[1]))
        return float(out_n)
    if op in ("reduce-window", "select-and-scatter"):
        return float(out_n) * 9.0  # window size unknown; assume 3x3-ish
    if op == "rng-bit-generator":
        return 16.0 * out_n
    # default: elementwise-ish, 1 flop/element
    return float(out_n)


def _instr_bytes(ins: _Instr) -> float:
    if ins.dims is None or ins.dtype is None:
        return 0.0
    return float(_numel(ins.dims)) * _DTYPE_BYTES.get(ins.dtype, 4)


def _entry_name(comps: Dict[str, List[_Instr]]) -> str:
    """The ENTRY computation: jax names it e.g. "main.123"; fall back
    to the last computation parsed."""
    entry = None
    for name in comps:
        if name.startswith("main"):
            entry = name
    return entry if entry is not None else list(comps.keys())[-1]


def _module_shapes(comps: Dict[str, List[_Instr]]) -> Dict[str, tuple]:
    """name -> (dtype, dims) over every instruction in the module."""
    shapes: Dict[str, tuple] = {}
    for instrs in comps.values():
        for ins in instrs:
            if ins.dims is not None:
                shapes[ins.name] = (ins.dtype, ins.dims)
    return shapes


def _op_label(ins: _Instr) -> str:
    """Framework-op attribution for one instruction: the named_scope
    op_name path (jit prefix stripped), else the HLO value name."""
    opname = _OPNAME_RE.search(ins.line)
    label = opname.group(1) if opname else ins.name
    return re.sub(r"^jit\([^)]*\)/", "", label)


def _group_key(label: str, fallback: str) -> str:
    """Group label: the first two named_scope path segments (how both
    aggregate() and bytes_accessed() bucket per framework op)."""
    parts = [p for p in label.split("/") if p]
    return "/".join(parts[:2]) if parts else fallback


def profile_hlo(hlo_text: str) -> List[dict]:
    """Per top-level-instruction cost rows for the ENTRY computation.

    Returns rows {op, hlo, flops, out_bytes} where `op` is the
    framework-level op_name path (from named_scope metadata) and
    fusions include their fused computation's FLOPs.
    """
    comps = _parse_computations(hlo_text)
    if not comps:
        return []
    entry = _entry_name(comps)
    shapes = _module_shapes(comps)

    # FLOPs per computation (for fusion attribution); resolve nested
    # calls iteratively to a fixed point.
    comp_flops: Dict[str, float] = {}
    for _ in range(4):
        for cname, instrs in comps.items():
            total = 0.0
            for ins in instrs:
                if ins.opcode == "fusion" or ins.opcode in ("call", "map"):
                    cm = _CALLS_RE.search(ins.line)
                    if cm:
                        total += comp_flops.get(cm.group(1), 0.0)
                        continue
                total += _instr_flops(ins, shapes)
            comp_flops[cname] = total

    rows: List[dict] = []
    for ins in comps[entry]:
        if ins.opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element"):
            continue
        if ins.opcode in ("fusion", "call", "map"):
            cm = _CALLS_RE.search(ins.line)
            flops = comp_flops.get(cm.group(1), 0.0) if cm else 0.0
        else:
            flops = _instr_flops(ins, shapes)
        rows.append({"op": _op_label(ins), "hlo": ins.opcode,
                     "flops": flops, "out_bytes": _instr_bytes(ins)})
    return rows


def _operand_bytes(ins: _Instr, shapes: Dict[str, tuple]) -> float:
    """Bytes read by one instruction: sum of operand shapes. Operand
    tokens in optimized HLO text carry their type (`f32[2,3]{1,0}
    %name`) — parse it directly; bare `%name` tokens fall back to the
    module-wide shape map."""
    m = _OPERANDS_RE.search(ins.line)
    if not m:
        return 0.0
    total = 0.0
    # split on ", " (the operand separator): dims inside `f32[8,12]`
    # carry bare commas and must not split
    for tok in m.group(1).split(", "):
        tok = tok.strip()
        sh = _shape_of(tok)
        if sh is None:
            name = tok.lstrip("%").split(" ")[0]
            sh = shapes.get(name)
        if sh is not None:
            total += float(_numel(sh[1])) * _DTYPE_BYTES.get(sh[0], 4)
    return total


def bytes_accessed(hlo_text: str) -> dict:
    """Estimated HBM bytes accessed by the program's ENTRY computation:
    per top-level instruction, operand bytes (reads) + result bytes
    (writes). Fusion-internal temporaries don't count — exactly the
    property that makes this the byte-diet meter: a knob that keeps
    data half-width ACROSS fusion boundaries (bf16 optimizer slots,
    bf16 BN statistics) shows up here, CPU-verifiable, no chip needed.

    Returns {"total": float, "reads": float, "writes": float,
    "by_op": {framework-op-path: bytes}} — `by_op` groups by the same
    named_scope attribution `aggregate()` uses.
    """
    comps = _parse_computations(hlo_text)
    if not comps:
        return {"total": 0.0, "reads": 0.0, "writes": 0.0, "by_op": {}}
    shapes = _module_shapes(comps)
    reads = writes = 0.0
    by_op: Dict[str, float] = {}
    for ins in comps[_entry_name(comps)]:
        if ins.opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element", "bitcast"):
            continue
        r = _operand_bytes(ins, shapes)
        w = _instr_bytes(ins)
        reads += r
        writes += w
        key = _group_key(_op_label(ins), ins.opcode)
        by_op[key] = by_op.get(key, 0.0) + r + w
    return {"total": reads + writes, "reads": reads, "writes": writes,
            "by_op": by_op}


def aggregate(rows: List[dict], top: int = 0) -> List[dict]:
    """Group rows by framework op (first two named_scope segments)."""
    groups: Dict[str, dict] = {}
    for r in rows:
        key = _group_key(r["op"], r["hlo"])
        g = groups.setdefault(key, {"op": key, "flops": 0.0,
                                    "out_bytes": 0.0, "count": 0})
        g["flops"] += r["flops"]
        g["out_bytes"] += r["out_bytes"]
        g["count"] += 1
    out = sorted(groups.values(), key=lambda g: -g["flops"])
    return out[:top] if top else out


def format_table(rows: List[dict], measured_step_s: Optional[float] = None,
                 top: int = 25) -> str:
    """Human-readable graph profile table (printed by
    Device.PrintTimeProfiling when graph-mode profiles exist)."""
    agg = aggregate(rows, top=top)
    total_flops = sum(r["flops"] for r in rows) or 1.0
    lines = ["Graph (XLA) cost profile"
             + (f"  [measured step: {measured_step_s * 1e3:.2f} ms]"
                if measured_step_s else "")
             + f"  total ~{total_flops / 1e9:.2f} GFLOP:"]
    for g in agg:
        pct = 100.0 * g["flops"] / total_flops
        est = (f"  est {measured_step_s * g['flops'] / total_flops * 1e3:8.3f} ms"
               if measured_step_s else "")
        lines.append(
            f"  OP = {g['op']:<40} FLOPs = {g['flops'] / 1e6:12.2f} M "
            f"({pct:5.1f}%) x {g['count']:<4d}{est}")
    return "\n".join(lines)
