"""Graph-mode per-op profiling: XLA HLO cost breakdown.

Reference parity: the reference times every graph node with cudaEvent
pairs inside `Graph::Run` and prints a per-op table via
`Device::PrintTimeProfiling` (src/core/scheduler/scheduler.cc,
SURVEY.md §5). In the TPU design the whole training step is ONE fused
XLA program, so "per-op kernel times" do not exist post-fusion; the
honest equivalent is:

  * measured wall time of the compiled step (recorded by `_JitStep`
    into the device's op-time table), plus
  * a per-HLO-instruction cost breakdown of the optimized program —
    FLOPs computed analytically from dot/convolution dimension numbers,
    bytes from operand/result shapes — with each top-level instruction
    attributed back to the framework op that produced it via the
    `op_name` metadata that `autograd.Operator.__call__` stamps with
    `jax.named_scope`.

Estimated per-region time = (region FLOPs / program FLOPs) x measured
step time; the table is explicit that these are cost-model estimates,
not per-kernel measurements.

No TensorFlow/profiler-plugin dependency: this parses the HLO text
that PJRT already returns (`compiled.as_text()`).
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%name = f32[2,3]{1,0} opcode(...)` (also matches tuple-typed results
# loosely; those get shape=None).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<shape>[0-9,]*)\]\S*\s+"
    r"(?P<opcode>[\w\-]+)\(")
_TUPLE_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*\("
    r".*?\)\s+(?P<opcode>[\w\-]+)\(")
_OPERANDS_RE = re.compile(r"\(([^)]*)\)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIMLABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")


def _shape_of(type_str: str):
    m = re.match(r"([a-z][a-z0-9]*)\[([0-9,]*)\]", type_str)
    if not m:
        return None
    dims = [int(d) for d in m.group(2).split(",") if d] if m.group(2) else []
    return m.group(1), dims


def _numel(dims: List[int]) -> int:
    return int(math.prod(dims)) if dims else 1


def _split_operands(s: str) -> List[str]:
    """Split an operand list on top-level commas only — inline types
    (`f32[8,32]{1,0} %arg`) carry commas inside brackets/braces that a
    naive split would tear."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def _operand_shape(op_str: str, shapes: Dict[str, tuple]):
    """(dtype, dims) for one operand reference. Post-optimization text
    spells operands WITH their inline type (`f32[8,32]{1,0} %Arg_0.1`)
    — parse that directly; pre-optimization text spells just `%name`,
    which resolves through the module-wide shape table."""
    toks = op_str.split()
    if not toks:
        return None
    sh = _shape_of(toks[0])
    if sh:
        return sh
    return shapes.get(toks[-1].lstrip("%"))


class _Instr:
    __slots__ = ("name", "dtype", "dims", "opcode", "line")

    def __init__(self, name, dtype, dims, opcode, line):
        self.name, self.dtype, self.dims = name, dtype, dims
        self.opcode, self.line = opcode, line


def _parse_computations(hlo_text: str) -> Dict[str, List[_Instr]]:
    """Split module text into computations -> instruction lists."""
    comps: Dict[str, List[_Instr]] = {}
    current: Optional[str] = None
    for raw in hlo_text.splitlines():
        line = raw.rstrip()
        if current is None:
            s = line.strip()
            # Header forms: post-optimization text spells
            # `[ENTRY] %name (params) -> result {` and pre-optimization
            # HLO (`lowered.as_text(dialect="hlo")`) just
            # `[ENTRY] name {`. Matched structurally rather than by a
            # single regex: a while/scan BODY computation carries its
            # carry tuple as a parameter, and tuple-typed params nest
            # parens a regex can't bound (`%region_0.180.clone (arg:
            # (s32[], f32[5]{0}, ...)) -> (...) {`) — those bodies are
            # exactly what peak_bytes_estimate must see inside.
            if s.endswith("{") and " = " not in s.split("->")[0]:
                head = (s.split("->")[0] if "->" in s
                        else s[:-1].strip())
                tok = head.split()
                name = None
                if "->" in s and tok:
                    name = (tok[1] if tok[0] == "ENTRY"
                            and len(tok) > 1 else tok[0])
                elif len(tok) == 2 and tok[0] == "ENTRY":
                    name = tok[1]
                elif len(tok) == 1:
                    name = tok[0]
                if name:
                    name = name.lstrip("%").split("(")[0]
                if name:
                    current = name
                    comps[current] = []
            continue
        if line.strip() == "}":
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m:
            dims = ([int(d) for d in m.group("shape").split(",") if d]
                    if m.group("shape") else [])
            comps[current].append(_Instr(
                m.group("name"), m.group("dtype"), dims,
                m.group("opcode"), line))
            continue
        m = _TUPLE_INSTR_RE.match(line)
        if m:
            comps[current].append(_Instr(
                m.group("name"), None, None, m.group("opcode"), line))
    return comps


def _instr_flops(ins: _Instr, shapes: Dict[str, tuple]) -> float:
    """Analytic FLOPs for one instruction (0 for data movement)."""
    op = ins.opcode
    if op in ("parameter", "constant", "tuple", "get-tuple-element",
              "bitcast", "copy", "reshape", "transpose", "broadcast",
              "slice", "concatenate", "gather", "scatter", "pad",
              "dynamic-slice", "dynamic-update-slice", "iota",
              "convert", "reverse", "copy-start", "copy-done",
              "all-gather", "all-reduce", "reduce-scatter",
              "collective-permute", "partition-id", "replica-id"):
        return 0.0
    out_n = _numel(ins.dims) if ins.dims is not None else 0
    if op == "dot":
        m = _OPERANDS_RE.search(ins.line)
        c = _CONTRACT_RE.search(ins.line)
        if m and c:
            ops = _split_operands(m.group(1))
            lhs = _operand_shape(ops[0], shapes) if ops else None
            if lhs:
                cdims = [int(d) for d in c.group(1).split(",") if d]
                k = _numel([lhs[1][d] for d in cdims if d < len(lhs[1])])
                return 2.0 * out_n * k
        return 2.0 * out_n  # fallback
    if op == "convolution":
        m = _OPERANDS_RE.search(ins.line)
        dl = _DIMLABELS_RE.search(ins.line)
        if m and dl:
            ops = _split_operands(m.group(1))
            rhs = (_operand_shape(ops[1], shapes)
                   if len(ops) > 1 else None)
            if rhs:
                o_pos = dl.group(2).index("o")
                rhs_n = _numel(rhs[1])
                o_size = rhs[1][o_pos] if o_pos < len(rhs[1]) else 1
                return 2.0 * out_n * rhs_n / max(o_size, 1)
        return 2.0 * out_n
    if op in ("exponential", "log", "tanh", "logistic", "power", "rsqrt",
              "sqrt", "sine", "cosine", "erf", "atan2", "expm1",
              "log-plus-one", "cbrt"):
        return 8.0 * out_n  # transcendental: several flops each
    if op == "reduce":
        # ~1 flop per reduced input element; approximate via operand.
        m = _OPERANDS_RE.search(ins.line)
        if m:
            ops = _split_operands(m.group(1))
            src = _operand_shape(ops[0], shapes) if ops else None
            if src:
                return float(_numel(src[1]))
        return float(out_n)
    if op in ("reduce-window", "select-and-scatter"):
        return float(out_n) * 9.0  # window size unknown; assume 3x3-ish
    if op == "rng-bit-generator":
        return 16.0 * out_n
    # default: elementwise-ish, 1 flop/element
    return float(out_n)


def _instr_bytes(ins: _Instr) -> float:
    if ins.dims is None or ins.dtype is None:
        return 0.0
    return float(_numel(ins.dims)) * _DTYPE_BYTES.get(ins.dtype, 4)


def _entry_name(comps: Dict[str, List[_Instr]]) -> str:
    """The ENTRY computation: jax names it e.g. "main.123"; fall back
    to the last computation parsed."""
    entry = None
    for name in comps:
        if name.startswith("main"):
            entry = name
    return entry if entry is not None else list(comps.keys())[-1]


def _module_shapes(comps: Dict[str, List[_Instr]]) -> Dict[str, tuple]:
    """name -> (dtype, dims) over every instruction in the module."""
    shapes: Dict[str, tuple] = {}
    for instrs in comps.values():
        for ins in instrs:
            if ins.dims is not None:
                shapes[ins.name] = (ins.dtype, ins.dims)
    return shapes


def _op_label(ins: _Instr) -> str:
    """Framework-op attribution for one instruction: the named_scope
    op_name path (jit prefix stripped), else the HLO value name."""
    opname = _OPNAME_RE.search(ins.line)
    label = opname.group(1) if opname else ins.name
    return re.sub(r"^jit\([^)]*\)/", "", label)


def _group_key(label: str, fallback: str) -> str:
    """Group label: the first two named_scope path segments (how both
    aggregate() and bytes_accessed() bucket per framework op)."""
    parts = [p for p in label.split("/") if p]
    return "/".join(parts[:2]) if parts else fallback


def profile_hlo(hlo_text: str) -> List[dict]:
    """Per top-level-instruction cost rows for the ENTRY computation.

    Returns rows {op, hlo, flops, out_bytes} where `op` is the
    framework-level op_name path (from named_scope metadata) and
    fusions include their fused computation's FLOPs.
    """
    comps = _parse_computations(hlo_text)
    if not comps:
        return []
    entry = _entry_name(comps)
    shapes = _module_shapes(comps)

    # FLOPs per computation (for fusion attribution); resolve nested
    # calls iteratively to a fixed point.
    comp_flops: Dict[str, float] = {}
    for _ in range(4):
        for cname, instrs in comps.items():
            total = 0.0
            for ins in instrs:
                if ins.opcode == "fusion" or ins.opcode in ("call", "map"):
                    cm = _CALLS_RE.search(ins.line)
                    if cm:
                        total += comp_flops.get(cm.group(1), 0.0)
                        continue
                total += _instr_flops(ins, shapes)
            comp_flops[cname] = total

    rows: List[dict] = []
    for ins in comps[entry]:
        if ins.opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element"):
            continue
        if ins.opcode in ("fusion", "call", "map"):
            cm = _CALLS_RE.search(ins.line)
            flops = comp_flops.get(cm.group(1), 0.0) if cm else 0.0
        else:
            flops = _instr_flops(ins, shapes)
        rows.append({"op": _op_label(ins), "hlo": ins.opcode,
                     "flops": flops, "out_bytes": _instr_bytes(ins)})
    return rows


def _operand_bytes(ins: _Instr, shapes: Dict[str, tuple]) -> float:
    """Bytes read by one instruction: sum of operand shapes. Operand
    tokens in optimized HLO text carry their type (`f32[2,3]{1,0}
    %name`) — parse it directly; bare `%name` tokens fall back to the
    module-wide shape map."""
    m = _OPERANDS_RE.search(ins.line)
    if not m:
        return 0.0
    total = 0.0
    # split on ", " (the operand separator): dims inside `f32[8,12]`
    # carry bare commas and must not split
    for tok in m.group(1).split(", "):
        tok = tok.strip()
        sh = _shape_of(tok)
        if sh is None:
            name = tok.lstrip("%").split(" ")[0]
            sh = shapes.get(name)
        if sh is not None:
            total += float(_numel(sh[1])) * _DTYPE_BYTES.get(sh[0], 4)
    return total


def bytes_accessed(hlo_text: str) -> dict:
    """Estimated HBM bytes accessed by the program's ENTRY computation:
    per top-level instruction, operand bytes (reads) + result bytes
    (writes). Fusion-internal temporaries don't count — exactly the
    property that makes this the byte-diet meter: a knob that keeps
    data half-width ACROSS fusion boundaries (bf16 optimizer slots,
    bf16 BN statistics) shows up here, CPU-verifiable, no chip needed.

    Returns {"total": float, "reads": float, "writes": float,
    "by_op": {framework-op-path: bytes}} — `by_op` groups by the same
    named_scope attribution `aggregate()` uses.
    """
    comps = _parse_computations(hlo_text)
    if not comps:
        return {"total": 0.0, "reads": 0.0, "writes": 0.0, "by_op": {}}
    shapes = _module_shapes(comps)
    reads = writes = 0.0
    by_op: Dict[str, float] = {}
    for ins in comps[_entry_name(comps)]:
        if ins.opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element", "bitcast"):
            continue
        r = _operand_bytes(ins, shapes)
        w = _instr_bytes(ins)
        reads += r
        writes += w
        key = _group_key(_op_label(ins), ins.opcode)
        by_op[key] = by_op.get(key, 0.0) + r + w
    return {"total": reads + writes, "reads": reads, "writes": writes,
            "by_op": by_op}


# Instructions that call other computations whose internals DO
# materialize buffers (control flow). Fusions are deliberately opaque:
# a fusion's intermediates live in registers/VMEM, not HBM — counting
# them would overstate every fused program's peak.
_PEAK_RECURSE_OPS = ("while", "call", "conditional")
_CALLEE_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations)="
    r"(\{[^}]*\}|%?[\w.\-]+)")


def _instr_callees(ins: _Instr) -> List[str]:
    out = []
    for m in _CALLEE_RE.finditer(ins.line):
        val = m.group(1)
        if val.startswith("{"):
            out.extend(v.strip().lstrip("%")
                       for v in val[1:-1].split(",") if v.strip())
        else:
            out.append(val.lstrip("%"))
    return out


def _split_top(seg: str) -> List[str]:
    """Split on commas at bracket depth 0 — operand TYPES carry
    commas of their own (`f32[8,8]`, tuple types `(f32[], s32[])`)."""
    out, cur, depth = [], [], 0
    for ch in seg:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _operand_names(ins: _Instr) -> List[str]:
    """Operand value names of one instruction. The operand list is
    the balanced paren group FOLLOWING the opcode — `_OPERANDS_RE`
    (first paren group on the line) would grab the result TYPE of
    tuple-typed instructions (`%t = (f32[], s32[]) tuple(%a, %b)`),
    mis-freeing every value whose last use is a tuple/while/ROOT
    tuple and under-counting the peak."""
    idx = ins.line.find(ins.opcode + "(")
    if idx < 0:
        return []
    start = idx + len(ins.opcode)
    depth, end = 0, None
    for j in range(start, len(ins.line)):
        ch = ins.line[j]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                end = j
                break
    if end is None:
        return []
    out = []
    for tok in _split_top(ins.line[start + 1:end]):
        tok = tok.strip()
        if tok:
            out.append(tok.split(" ")[-1].lstrip("%"))
    return out


def _comp_peak(name: str, comps: Dict[str, List[_Instr]],
               memo: Dict[str, float]) -> float:
    """Max live bytes over one computation's instruction sequence:
    parameters are live throughout (the caller holds them), each
    result is live from its definition to its last textual use (the
    ROOT result to the end), and control-flow instructions add their
    callee computation's own peak as a transient at the call point
    (while takes max(body, condition) — they never run
    simultaneously). An analytic estimate, not a buffer-assignment
    readout — but it moves with the program's real liveness, which is
    what makes a remat knob's effect visible on CPU."""
    if name in memo:
        return memo[name]
    memo[name] = 0.0  # cycle guard (HLO call graphs are acyclic)
    instrs = comps.get(name, [])
    opnames = [_operand_names(ins) for ins in instrs]
    last_use: Dict[str, int] = {}
    for i, names in enumerate(opnames):
        for nm in names:
            last_use[nm] = i
    base = sum(_instr_bytes(i) for i in instrs
               if i.opcode == "parameter")
    cur = peak = base
    live: Dict[str, float] = {}
    for i, ins in enumerate(instrs):
        if ins.opcode == "parameter":
            continue
        b = _instr_bytes(ins)
        live[ins.name] = b
        cur += b
        transient = 0.0
        if ins.opcode in _PEAK_RECURSE_OPS:
            subs = [_comp_peak(c, comps, memo)
                    for c in _instr_callees(ins) if c in comps]
            if ins.opcode == "while" and subs:
                transient = max(subs)
            else:
                transient = sum(subs) if ins.opcode == "call" \
                    else (max(subs) if subs else 0.0)
        if cur + transient > peak:
            peak = cur + transient
        for nm in opnames[i]:
            if last_use.get(nm) == i:
                cur -= live.pop(nm, 0.0)
        if (not ins.line.lstrip().startswith("ROOT")
                and last_use.get(ins.name, i) <= i):
            cur -= live.pop(ins.name, 0.0)
    memo[name] = peak
    return peak


def peak_bytes_estimate(hlo_text: str) -> float:
    """Estimated peak live bytes of the program's ENTRY computation:
    the max, over the instruction sequence, of (parameters + results
    still awaiting a later use + the internal peak of any control-flow
    callee active at that point). The memory-side companion of
    `bytes_accessed`: a byte-DIET knob (bf16 slots/stats) moves the
    traffic meter; a REMAT knob (`device.set_remat_policy`) moves this
    one — fewer activations survive the fwd→bwd boundary, so the max
    live set shrinks even though recompute adds traffic. CPU-
    verifiable via `Model.step_hlo_text`, no chip needed
    (tests/test_remat_policy.py pins that `dots_saveable` strictly
    lowers it for a conv model under grad accumulation)."""
    comps = _parse_computations(hlo_text)
    if not comps:
        return 0.0
    return _comp_peak(_entry_name(comps), comps, {})


def aggregate(rows: List[dict], top: int = 0) -> List[dict]:
    """Group rows by framework op (first two named_scope segments)."""
    groups: Dict[str, dict] = {}
    for r in rows:
        key = _group_key(r["op"], r["hlo"])
        g = groups.setdefault(key, {"op": key, "flops": 0.0,
                                    "out_bytes": 0.0, "count": 0})
        g["flops"] += r["flops"]
        g["out_bytes"] += r["out_bytes"]
        g["count"] += 1
    out = sorted(groups.values(), key=lambda g: -g["flops"])
    return out[:top] if top else out


def format_table(rows: List[dict], measured_step_s: Optional[float] = None,
                 top: int = 25) -> str:
    """Human-readable graph profile table (printed by
    Device.PrintTimeProfiling when graph-mode profiles exist)."""
    agg = aggregate(rows, top=top)
    total_flops = sum(r["flops"] for r in rows) or 1.0
    lines = ["Graph (XLA) cost profile"
             + (f"  [measured step: {measured_step_s * 1e3:.2f} ms]"
                if measured_step_s else "")
             + f"  total ~{total_flops / 1e9:.2f} GFLOP:"]
    for g in agg:
        pct = 100.0 * g["flops"] / total_flops
        est = (f"  est {measured_step_s * g['flops'] / total_flops * 1e3:8.3f} ms"
               if measured_step_s else "")
        lines.append(
            f"  OP = {g['op']:<40} FLOPs = {g['flops'] / 1e6:12.2f} M "
            f"({pct:5.1f}%) x {g['count']:<4d}{est}")
    return "\n".join(lines)
