"""Decoder-only transformer LM — the multi-chip flagship.

No reference equivalent (SINGA's only transformer is the SONNX-imported
BERT, examples/onnx/bert); this model exists to exercise every
parallelism axis natively:

  * DP   — batch dim over "data" (mesh-mode `Model.compile`);
  * TP   — q/k/v/o and MLP GEMMs sharded over "model" via the default
           `parallel.ShardingRules` (Megatron-style column parallel);
  * SP   — ring attention over "seq" (parallel/ring_attention.py):
           sequence length scales with the number of chips;
all inside one jit-ed train step where XLA inserts the ICI collectives.
"""
from __future__ import annotations

import numpy as np

from .. import autograd, layer, model, quant as quant_mod, tensor


_NORM_CLS = {"layer": layer.LayerNorm, "rms": layer.RMSNorm}


def _norm_cls(norm: str):
    try:
        return _NORM_CLS[norm]
    except KeyError:
        raise ValueError(
            f"norm must be one of {sorted(_NORM_CLS)}, got {norm!r}"
        ) from None


class TransformerBlock(layer.Layer):
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x))."""

    def __init__(self, num_heads: int, d_ff: int, causal: bool = True,
                 mesh=None, dropout: float = 0.0, norm: str = "layer",
                 name=None):
        super().__init__(name)
        norm_cls = _norm_cls(norm)
        self.ln1 = norm_cls()
        self.attn = layer.MultiHeadAttention(num_heads, causal=causal,
                                             mesh=mesh, dropout=dropout)
        self.ln2 = norm_cls()
        self.fc1 = layer.Linear(d_ff)
        self.act = layer.Gelu()
        self.fc2 = layer.Linear(0)  # lazily sized to d_model
        self.drop = layer.Dropout(dropout) if dropout else None

    def initialize(self, x):
        self.fc2.num_output = x.shape[-1]

    def forward(self, x):
        x = autograd.add(x, self.attn(self.ln1(x)))
        h = self.fc2(self.act(self.fc1(self.ln2(x))))
        if self.drop is not None:
            h = self.drop(h)
        return autograd.add(x, h)


class TransformerLM(model.Model):
    """Causal LM over int token ids [B, S] → logits [B, S, vocab]."""

    def __init__(self, vocab_size: int, d_model: int = 256,
                 num_heads: int = 8, num_layers: int = 4,
                 d_ff: int | None = None, max_len: int = 1024,
                 mesh=None, dropout: float = 0.0,
                 tie_embeddings: bool = False, norm: str = "layer"):
        super().__init__()
        _norm_cls(norm)  # validate early, shared message
        d_ff = d_ff or 4 * d_model
        self.vocab_size = vocab_size
        self.max_len = max_len
        self.tie_embeddings = tie_embeddings
        self.norm = norm
        self.embed = layer.Embedding(vocab_size, d_model)
        self.pos_embed = layer.Embedding(max_len, d_model)
        self.blocks = layer.Sequential(*[
            TransformerBlock(num_heads, d_ff, causal=True, mesh=mesh,
                             dropout=dropout, norm=norm)
            for _ in range(num_layers)
        ])
        self.ln_f = _norm_cls(norm)()
        # tied: logits = h @ W_embed^T (gradients flow into the
        # embedding from both uses); untied: separate projection
        self.head = (None if tie_embeddings
                     else layer.Linear(vocab_size, bias=False))

    def forward(self, x):
        B, S = x.shape
        pos = tensor.from_numpy(np.arange(S, dtype=np.int32))
        if x.device is not None:
            pos = pos.to_device(x.device)
        h = autograd.add(self.embed(x), self.pos_embed(pos))
        h = self.blocks(h)
        h = self.ln_f(h)
        if self.tie_embeddings:
            return autograd.matmul(
                h, autograd.transpose(self.embed.W, (1, 0)))
        return self.head(h)

    def train_one_batch(self, x, y):
        out = self.forward(x)                      # [B, S, V]
        logits = autograd.reshape(out, (-1, self.vocab_size))
        labels = autograd.reshape(y, (-1,))
        loss = autograd.softmax_cross_entropy(logits, labels)
        self._optimizer.backward_and_update(loss)
        return out, loss


    # -- jitted KV-cache generation (inference path) --------------------
    #
    # TPU-native incremental decoding: a static-shape KV cache
    # [L, 2, B, H, P+max_new, D] plus a lax.scan decode loop, compiled
    # once. The math mirrors the training stack exactly (pre-norm
    # blocks, exact-erf gelu, 1/sqrt(D) attention scale); the parity
    # test pins greedy decode against full-context forward argmax.

    def _decode_params(self):
        import jax.numpy as jnp

        def lin(l):
            return (l.W.data, l.b.data if l.bias else None)

        def ln(l):
            # (g, eps) = RMSNorm, (g, b, eps) = LayerNorm — tuple
            # LENGTH is the dispatch (strings can't be jit pytree
            # leaves; eps floats can)
            if isinstance(l, layer.RMSNorm):
                return (l.gamma.data, l.eps)
            return (l.gamma.data, l.beta.data, l.eps)

        blocks = []
        for blk in self.blocks._seq:
            a = blk.attn
            blocks.append({
                "ln1": ln(blk.ln1),
                "q": lin(a.q_proj), "k": lin(a.k_proj),
                "v": lin(a.v_proj), "o": lin(a.o_proj),
                "ln2": ln(blk.ln2),
                "fc1": lin(blk.fc1), "fc2": lin(blk.fc2),
            })
        if self.tie_embeddings:
            # memoize the transposed view per embedding buffer: a
            # fresh .T array every call would defeat the TP
            # shard-cache's leaf-identity check in generate()
            src = self.embed.W.data
            cached = getattr(self, "_tied_head", None)
            if cached is None or cached[0] is not src:
                self._tied_head = (src, jnp.asarray(src).T)
            head = self._tied_head[1]
        else:
            head = jnp.asarray(self.head.W.data)
        return {
            "embed": self.embed.W.data, "pos": self.pos_embed.W.data,
            "blocks": blocks,
            "ln_f": ln(self.ln_f),
            "head": head,
        }

    def _decode_params_quant(self):
        """Int8 view of `_decode_params()` (ISSUE 19): linear entries
        become length-3 (payload, scale, bias) tuples — tuple LENGTH
        is the dispatch, the `_ln` idiom — and embed/pos/head become
        (payload, scale) pairs with broadcast-shaped scales. Memoized
        on the fp32 leaf identities (the `_gen_shard_cache` contract):
        a training step between decodes invalidates the copy."""
        import jax
        import jax.numpy as jnp

        base = self._decode_params()
        leaf_ids = tuple(id(l) for l in
                         jax.tree_util.tree_leaves(base))
        cached = getattr(self, "_quant_params_cache", None)
        if cached is not None and cached[0] == leaf_ids:
            return cached[1]
        qp = quant_mod.quantize_decode_params(base)

        def pair(t):  # device-put payload/scale once, not per step
            return ((jnp.asarray(t[0]), jnp.asarray(t[1])) + t[2:]
                    if isinstance(t, tuple) else t)

        qp["embed"] = pair(qp["embed"])
        qp["pos"] = pair(qp["pos"])
        qp["head"] = pair(qp["head"])
        for blk in qp["blocks"]:
            for k in ("q", "k", "v", "o", "fc1", "fc2"):
                blk[k] = pair(blk[k])
        self._quant_params_cache = (leaf_ids, qp)
        return qp

    @staticmethod
    def _table(spec, idx):
        """Embedding-style lookup for either param form: a plain
        array, or a quantized (payload, scale) pair with per-row
        scales — gather both planes, dequantize in fp32."""
        import jax.numpy as jnp

        if isinstance(spec, tuple):
            q, s = spec
            return q[idx].astype(s.dtype) * s[idx]
        return spec[idx]

    @staticmethod
    def _head_matmul(last, head, prec):
        import jax.numpy as jnp

        if isinstance(head, tuple):
            q, s = head
            return jnp.matmul(last, q.astype(last.dtype),
                              precision=prec) * s
        return jnp.matmul(last, head, precision=prec)

    @staticmethod
    def _ln(x, spec):
        import jax.numpy as jnp

        if len(spec) == 2:  # RMSNorm: (gamma, eps)
            g, eps = spec
            return x / jnp.sqrt(
                jnp.mean(jnp.square(x), -1, keepdims=True) + eps) * g
        g, b, eps = spec
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + eps) * g + b

    def _stack_step(self, params, ids, cache, pos0, last_index=None):
        """Run S tokens (positions pos0..pos0+S-1) through the block
        stack, writing their K/V into `cache` at those slots and
        attending over every filled slot. Returns (last-token logits,
        new cache). Works for both prefill (S=P) and decode (S=1).
        `last_index` (traced scalar) selects which row's logits to
        return instead of the last — bucket-padded prefill reads the
        REAL last prompt token, not the pad tail."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        H = self.blocks._seq[0].attn.num_heads
        B, S = ids.shape
        # quantized stacked cache (ISSUE 19): (payload int8
        # [L,2,B,H,T,D], scale f32 [L,2,B,T]) instead of one fp32
        # array — tuple-ness is the dispatch, like the _ln specs
        qcache = isinstance(cache, tuple)
        if qcache:
            new_pay, new_sc = cache
            maxT = new_pay.shape[-2]
        else:
            new_cache = cache
            maxT = cache.shape[-2]
        h = self._table(params["embed"], ids) \
            + self._table(params["pos"], pos0 + jnp.arange(S))
        E = h.shape[-1]
        D = E // H
        scale = 1.0 / float(np.sqrt(D))
        # query i (absolute pos0+i) may attend cache slot j <= pos0+i
        mask = (pos0 + jnp.arange(S))[:, None] >= jnp.arange(maxT)[None, :]
        neg = jnp.asarray(jnp.finfo(h.dtype).min / 2, h.dtype)

        prec = tensor.get_matmul_precision()

        def lin(x, wb):
            if len(wb) == 3:  # quantized: (payload, scale, bias) —
                # dequant COMMUTES through the matmul (per-output-
                # channel scale), so accumulation is fp32 and the
                # fp32 weight copy is never materialised
                qw, ws, b = wb
                y = jnp.matmul(x, qw.astype(x.dtype),
                               precision=prec) * ws
            else:
                w, b = wb
                y = jnp.matmul(x, w, precision=prec)
            return y if b is None else y + b

        for li, blk in enumerate(params["blocks"]):
            x = self._ln(h, blk["ln1"])

            def split(t):  # [B,S,E] -> [B,H,S,D]
                return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

            q = split(lin(x, blk["q"]))
            kk = split(lin(x, blk["k"]))
            vv = split(lin(x, blk["v"]))
            kv = jnp.stack([kk, vv])
            if qcache:
                # per-position scales (reduce over H, D ONLY — the
                # same extent the S=1 step uses, which is what makes
                # chunked replay bit-exact against per-step decode)
                qkv, sc = quant_mod.quantize_kv(kv)
                new_pay = lax.dynamic_update_slice(
                    new_pay, qkv[None], (li, 0, 0, 0, pos0, 0))
                new_sc = lax.dynamic_update_slice(
                    new_sc, sc[None], (li, 0, 0, pos0))
                kv_all = quant_mod.dequantize_kv(
                    lax.dynamic_index_in_dim(new_pay, li, 0,
                                             keepdims=False),
                    lax.dynamic_index_in_dim(new_sc, li, 0,
                                             keepdims=False))
                k_all, v_all = kv_all[0], kv_all[1]
            else:
                new_cache = lax.dynamic_update_slice(
                    new_cache, kv[None], (li, 0, 0, 0, pos0, 0))
                k_all = lax.dynamic_index_in_dim(new_cache, li, 0,
                                                 keepdims=False)[0]
                v_all = lax.dynamic_index_in_dim(new_cache, li, 0,
                                                 keepdims=False)[1]
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_all,
                           precision=prec) * scale
            s = jnp.where(mask[None, None], s, neg)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, v_all, precision=prec)
            o = o.transpose(0, 2, 1, 3).reshape(B, S, E)
            h = h + lin(o, blk["o"])
            x = self._ln(h, blk["ln2"])
            h = h + lin(jax.nn.gelu(lin(x, blk["fc1"]),
                                    approximate=False), blk["fc2"])
        h = self._ln(h, params["ln_f"])
        if last_index is None:
            last = h[:, -1]
        elif getattr(last_index, "ndim", 0) == 1:
            # per-row last index ([B] vector) — cohort prefill packs
            # sessions with different real prompt lengths into one
            # bucket-padded batch; each row reads ITS last real token
            last = jnp.take_along_axis(
                h, last_index[:, None, None], axis=1)[:, 0]
        else:
            last = lax.dynamic_index_in_dim(h, last_index, 1,
                                            keepdims=False)
        return (self._head_matmul(last, params["head"], prec),
                (new_pay, new_sc) if qcache else new_cache)

    def _program_cache(self):
        """`_gen_cache`: the model's compiled decode-program cache —
        a bounded `stats.TieredLRUCache` sharing the process-wide
        `cache_stats()["decode"]` counters (was an unbounded dict;
        a long-lived server cycling sampling configs and shapes must
        evict, not grow)."""
        from .. import stats as stats_mod

        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = stats_mod.TieredLRUCache(
                "decode", stats=stats_mod.decode_stats().cache)
        return cache

    @staticmethod
    def _count_first_trace(fn):
        """Time `fn`'s first invocation (trace + compile + run) into
        the decode CacheStats — the retrace-storm signal for the
        decode tier."""
        import time

        import jax

        from .. import stats as stats_mod

        state = [True]

        def wrapped(*a):
            if state[0]:
                state[0] = False
                t0 = time.perf_counter()
                out = fn(*a)
                jax.block_until_ready(out)
                stats_mod.decode_stats().cache.record_trace(
                    time.perf_counter() - t0)
                return out
            return fn(*a)

        return wrapped

    def _compiled_decode(self, B, P, max_new, temperature, top_k):
        """Build (or fetch) the jitted prefill+scan decode program for
        this (shapes, sampling config) combination. Cached on the
        model so repeat generate() calls skip the XLA compile."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        key_ = (B, P, max_new, float(temperature), int(top_k),
                autograd._policy_key())  # policy baked in at trace time
        cache_dict = self._program_cache()
        hit = cache_dict.get(key_)
        if hit is not None:
            return hit

        def sample(logits, key):
            if temperature == 0.0:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            z = logits / temperature
            if top_k > 0:
                k = min(top_k, int(logits.shape[-1]))
                kth = lax.top_k(z, k)[0][..., -1:]
                z = jnp.where(z < kth, -jnp.inf, z)
            return jax.random.categorical(key, z).astype(jnp.int32)

        @jax.jit
        def run(params, prompt, cache, key):
            logits, cache = self._stack_step(params, prompt, cache, 0)
            key, sub = jax.random.split(key)
            tok = sample(logits, sub)

            def step(carry, _):
                cache, tok, pos, key = carry
                logits, cache = self._stack_step(
                    params, tok[:, None], cache, pos)
                key, sub = jax.random.split(key)
                nxt = sample(logits, sub)
                return (cache, nxt, pos + 1, key), tok

            (_, last, _, _), toks = lax.scan(
                step, (cache, tok, jnp.int32(P), key), None,
                length=max_new - 1) if max_new > 1 else (
                (None, tok, None, None),
                jnp.zeros((0, B), jnp.int32))
            return jnp.concatenate([toks.T, last[:, None]], axis=1)

        run = self._count_first_trace(run)
        cache_dict[key_] = run
        return run

    # -- token-granularity decode tier (ISSUE 16) -----------------------
    #
    # generate() fuses prefill + the whole decode loop into one
    # program per request shape; a serving tier needs the OPPOSITE
    # factoring — ONE warm single-step executable shared by every
    # in-flight session, so sequences can join/leave the fused batch
    # between steps. decode_step / prefill_step / sample_fn are that
    # factoring, with the bit-identity contract: a session decoded
    # through the shared slab reproduces generate()'s exact token
    # stream (same logits bits, same key-split sequence).

    def _slot_step(self, params, cache, tok, pos):
        """One fused decode step over every batch slot at PER-ROW
        positions: row b writes its K/V at cache slot (b, pos[b]) and
        attends slots 0..pos[b]. `cache` is a PER-LAYER list of
        [2, B, H, T, D] arrays — one buffer per layer, not one stacked
        [L, ...] slab — so XLA:CPU never materialises a whole-slab
        copy per layer (`at[li].set` on a stacked slab costs a full
        slab pass per layer; the per-layer list halves steady-state
        step time). The op sequence mirrors `_stack_step` S=1 exactly
        (same matmul/einsum forms, same mask constant) so a slab row
        decodes bitwise identically to the same request running alone
        through `generate()`. Returns (logits [B, V], new per-layer
        cache list)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        H = self.blocks._seq[0].attn.num_heads
        B = tok.shape[0]
        # quantized slab (ISSUE 19): per-layer (payload int8
        # [2,B,H,T,D], scale f32 [2,B,T]) tuples instead of plain
        # fp32 arrays — the update copy that dominates the step's
        # byte traffic shrinks 4x
        qcache = isinstance(cache[0], tuple)
        maxT = (cache[0][0] if qcache else cache[0]).shape[-2]
        h = self._table(params["embed"], tok[:, None]) \
            + self._table(params["pos"], pos)[:, None]
        E = h.shape[-1]
        D = E // H
        scale = 1.0 / float(np.sqrt(D))
        # row b (absolute position pos[b]) may attend slot j <= pos[b]
        mask = pos[:, None] >= jnp.arange(maxT)[None, :]      # [B, maxT]
        neg = jnp.asarray(jnp.finfo(h.dtype).min / 2, h.dtype)
        new_cache = []

        prec = tensor.get_matmul_precision()

        def lin(x, wb):
            if len(wb) == 3:  # (payload, scale, bias): dequant-at-
                # use, fp32 accumulation — see _stack_step
                qw, ws, b = wb
                y = jnp.matmul(x, qw.astype(x.dtype),
                               precision=prec) * ws
            else:
                w, b = wb
                y = jnp.matmul(x, w, precision=prec)
            return y if b is None else y + b

        for li, blk in enumerate(params["blocks"]):
            x = self._ln(h, blk["ln1"])

            def split(t):  # [B,1,E] -> [B,H,1,D]
                return t.reshape(B, 1, H, D).transpose(0, 2, 1, 3)

            q = split(lin(x, blk["q"]))
            kk = split(lin(x, blk["k"]))
            vv = split(lin(x, blk["v"]))
            kv = jnp.stack([kk, vv])                  # [2,B,H,1,D]

            def upd(c_row, kv_row, p):
                # c_row [2,H,T,D], kv_row [2,H,1,D]: write at slot p
                return lax.dynamic_update_slice(c_row, kv_row,
                                                (0, 0, p, 0))

            if qcache:
                # same per-position quantization as the chunked
                # prefill form (reduce over H, D) — the replay
                # bit-exactness lever
                qkv, sc = quant_mod.quantize_kv(kv)   # sc [2,B,1]
                payload, scp = cache[li]
                new_pay = jax.vmap(upd, in_axes=(1, 1, 0),
                                   out_axes=1)(payload, qkv, pos)

                def upds(s_row, sc_row, p):
                    # s_row [2,T], sc_row [2,1]: write at slot p
                    return lax.dynamic_update_slice(s_row, sc_row,
                                                    (0, p))

                new_sc = jax.vmap(upds, in_axes=(1, 1, 0),
                                  out_axes=1)(scp, sc, pos)
                new_cache.append((new_pay, new_sc))
                kv_all = quant_mod.dequantize_kv(new_pay, new_sc)
                k_all, v_all = kv_all[0], kv_all[1]
            else:
                new_li = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(
                    cache[li], kv, pos)
                new_cache.append(new_li)
                k_all = new_li[0]
                v_all = new_li[1]
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_all,
                           precision=prec) * scale
            s = jnp.where(mask[:, None, None], s, neg)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, v_all, precision=prec)
            o = o.transpose(0, 2, 1, 3).reshape(B, 1, E)
            h = h + lin(o, blk["o"])
            x = self._ln(h, blk["ln2"])
            h = h + lin(jax.nn.gelu(lin(x, blk["fc1"]),
                                    approximate=False), blk["fc2"])
        h = self._ln(h, params["ln_f"])
        return (self._head_matmul(h[:, -1], params["head"], prec),
                new_cache)

    def _aot_step(self, kind, jitted, args, extras):
        """Route a decode-tier step through the AOT store when armed:
        load the serialized executable (no trace) or trace once +
        publish, falling back to the plain jit on store miss/failure.
        `args` must be the CONCRETE first-call arguments."""
        import jax

        from .. import export_cache

        if not export_cache.active():
            return self._count_first_trace(jitted)
        key, parts = export_cache.step_key(self, None, kind, args,
                                           extras=extras)
        exp = export_cache.load(key)
        if exp is None:
            exp = export_cache.export_and_save(key, parts, jitted,
                                               args)
            if exp is None:
                return self._count_first_trace(jitted)
        return jax.jit(exp.call)

    def decode_step(self, params, cache, tok, pos):
        """ONE fused decode step for the serving tier: advance every
        slab row by one token (`tok` [B] int32 at per-row positions
        `pos` [B] int32), returning (next-token logits [B, V], new
        cache). `cache` is the per-layer list `_slot_step` documents.
        Compiled once per slab shape — the one warm executable
        continuous batching dispatches every step — and AOT-exported
        through export_cache when the store is armed."""
        cache_dict = self._program_cache()
        key_ = ("slot_step", quant_mod.cache_sig(cache),
                autograd._policy_key())
        fn = cache_dict.get(key_)
        if fn is None:
            import jax

            jitted = jax.jit(
                lambda p, c, t, po: self._slot_step(p, c, t, po))
            args = (params, list(cache), tok, pos)
            fn = self._aot_step(
                "decode_step", jitted, args,
                extras={"slab": self._slab_extra(cache),
                        "policy": autograd._policy_key()})
            cache_dict[key_] = fn
        return fn(params, list(cache), tok, pos)

    def decode_step_hlo(self, params, cache, tok, pos,
                        optimized: bool = True) -> str:
        """HLO text of the fused decode step at this exact slab
        geometry — input to `hlo_profile.bytes_accessed`, the byte
        meter the int8 KV/weight diet is gated on (ISSUE 19): the
        quantized step must access STRICTLY fewer bytes than the
        fp32 step at the same geometry, post-XLA-optimization (so a
        convert that materializes the fp32 copy would fail the gate,
        not hide inside it)."""
        import jax

        jitted = jax.jit(lambda p, c, t, po: self._slot_step(p, c, t, po))
        lowered = jitted.lower(params, list(cache), tok, pos)
        return (lowered.compile().as_text() if optimized
                else lowered.as_text())

    @staticmethod
    def _slab_extra(cache):
        """Export-key extras fragment for a decode slab: shapes for
        the plain form, shapes + quant marker for the packed form —
        an int8 slab artifact must never be loaded for an fp32 slab
        (or vice versa)."""
        if quant_mod.is_quant_cache(cache):
            return {"quant": "int8",
                    "payload": [list(p.shape) for p, _ in cache],
                    "scale": [list(s.shape) for _, s in cache]}
        return [list(c.shape) for c in cache]

    def decode_scan(self, params, cache, tok, pos, k):
        """`k` GREEDY fused decode steps in ONE program (`lax.scan`
        over `_slot_step` + in-graph argmax). XLA updates the scan's
        cache carry in place — the per-dispatch whole-slab copy that
        JAX's CPU backend cannot elide (no buffer donation) is paid
        once per BLOCK instead of once per token, which is where the
        serving tier's throughput win over sequential `generate()`
        comes from. In-graph `jnp.argmax` is the exact greedy program
        `generate()` scans with (and equals host `np.argmax` on
        identical logits bits — both first-max-wins), so a block
        decodes bit-identically to k single steps. Returns
        (toks [k, B] — one sampled token per step per row, new
        cache). The caller only dispatches a block when no session
        joins, leaves, expires, or samples within it."""
        import jax.numpy as jnp

        cache_dict = self._program_cache()
        key_ = ("slot_scan", int(k), quant_mod.cache_sig(cache),
                autograd._policy_key())
        fn = cache_dict.get(key_)
        if fn is None:
            import jax

            def scan_k(p, c, t, po):
                def body(carry, _):
                    c, t, po = carry
                    logits, c = self._slot_step(p, c, t, po)
                    t2 = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (c, t2, po + 1), t2

                (c, _t, _po), toks = jax.lax.scan(
                    body, (c, t, po), None, length=int(k))
                return toks, c

            jitted = jax.jit(scan_k)
            args = (params, list(cache), tok, pos)
            fn = self._aot_step(
                "decode_scan", jitted, args,
                extras={"slab": self._slab_extra(cache),
                        "block": int(k),
                        "policy": autograd._policy_key()})
            cache_dict[key_] = fn
        return fn(params, list(cache), tok, pos)

    def prefill_step(self, params, cache, ids, n_real):
        """Prefill one session's bucket-padded prompt: run `ids`
        [B, Pb] at positions 0..Pb-1, writing K/V into `cache`, and
        return (logits at row n_real-1 — the REAL last prompt token —
        [B, V], new cache). Pad rows beyond n_real do write K/V, but
        the causal mask hides them from every real prompt row and the
        decode steps overwrite slot p before any query can attend it,
        so bucketed prefill is exact, not approximate. Compiled once
        per (Pb, slab) shape; AOT-exported like decode_step."""
        import jax.numpy as jnp

        cache_dict = self._program_cache()
        key_ = ("prefill", ids.shape, cache.shape,
                jnp.asarray(cache).dtype.name, autograd._policy_key())
        fn = cache_dict.get(key_)
        if fn is None:
            import jax

            jitted = jax.jit(
                lambda p, c, i, n: self._stack_step(
                    p, i, c, 0, last_index=n - 1))
            args = (params, cache, ids, n_real)
            fn = self._aot_step(
                "prefill_step", jitted, args,
                extras={"prompt_bucket": list(ids.shape),
                        "slab": list(cache.shape),
                        "policy": autograd._policy_key()})
            cache_dict[key_] = fn
        return fn(params, cache, ids, n_real)

    def prefill_slab(self, params, slab, ids, n_real, slots):
        """Prefill a COHORT of bucket-padded prompts and scatter their
        K/V into slab rows `slots` in a single program: `_stack_step`
        runs `ids` [Bp, Pb] against a fresh Pb-wide cache materialised
        in-graph, each row reads its own last real token's logits
        (`n_real` [Bp] int32), and every layer's rows land in the slab
        via one scatter. Param streaming — the dominant prefill cost
        on memory-bound hosts — is paid once per cohort instead of
        once per session, the same amortization the fused decode step
        applies. The slab keeps its stale tail beyond Pb; decode
        overwrites position p before any query attends it (see
        `prefill_step`'s pad argument). `slots` [Bp] int32 is traced —
        one executable per (Bp, Pb) serves every row assignment.
        Returns (logits [Bp, V], new slab)."""
        import jax.numpy as jnp

        cache_dict = self._program_cache()
        key_ = ("prefill_slab", ids.shape,
                quant_mod.cache_sig(slab),
                autograd._policy_key())
        fn = cache_dict.get(key_)
        if fn is None:
            import jax

            L = len(slab)
            qslab = quant_mod.is_quant_cache(slab)
            c0 = slab[0][0] if qslab else slab[0]
            H = int(c0.shape[2])
            D = int(c0.shape[4])

            if qslab:
                def pf(p, sl, i, n, s):
                    # fresh Pb-wide QUANTIZED cache in-graph: the
                    # chunked _stack_step writes the same payload +
                    # scale planes the per-step chain would (see
                    # quantize_kv), then both planes scatter into
                    # the slab rows in one program
                    Bp, Pb = i.shape
                    c1 = (jnp.zeros((L, 2, Bp, H, Pb, D), jnp.int8),
                          jnp.zeros((L, 2, Bp, Pb), jnp.float32))
                    logits, c1 = self._stack_step(p, i, c1, 0,
                                                  last_index=n - 1)
                    pay, sc = c1
                    new = [(sl[li][0].at[:, s, :, :Pb, :]
                            .set(pay[li]),
                            sl[li][1].at[:, s, :Pb].set(sc[li]))
                           for li in range(L)]
                    return logits, new
            else:
                def pf(p, sl, i, n, s):
                    Bp, Pb = i.shape
                    c1 = jnp.zeros((L, 2, Bp, H, Pb, D), sl[0].dtype)
                    logits, c1 = self._stack_step(p, i, c1, 0,
                                                  last_index=n - 1)
                    new = [sl[li].at[:, s, :, :Pb, :].set(c1[li])
                           for li in range(L)]
                    return logits, new

            jitted = jax.jit(pf)
            args = (params, list(slab), ids, n_real, slots)
            fn = self._aot_step(
                "prefill_slab", jitted, args,
                extras={"prompt_bucket": list(ids.shape),
                        "slab": self._slab_extra(slab),
                        "policy": autograd._policy_key()})
            cache_dict[key_] = fn
        return fn(params, list(slab), ids, n_real, slots)

    def export_slab_rows(self, slab, slot, pos):
        """Snapshot one session's live K/V out of the decode slab as a
        single host array [L, 2, H, pos, D] — the portable half of KV
        migration. Pure host-side gather (no compile): the slab leaves
        are device arrays, `np.asarray` forces the transfer, and only
        the first `pos` sequence rows are real (the tail past `pos` is
        stale garbage decode would overwrite anyway, so it never
        crosses the wire). A QUANTIZED slab exports the PACKED form —
        (payload int8 [L, 2, H, pos, D], scale f32 [L, 2, pos]) — so
        live migration ships ~4x fewer bytes (ISSUE 19)."""
        if quant_mod.is_quant_cache(slab):
            quant_mod.stats_counters()["packed_kv_exports"] += 1
            return quant_mod.pack_slab_rows(slab, slot, pos)
        return np.stack(
            [np.asarray(c[:, slot, :, :pos, :]) for c in slab])

    def import_slab_rows(self, slab, slot, rows):
        """Transplant `export_slab_rows` output into row `slot` of a
        (possibly different-geometry) slab, returning the new slab.
        The seq dim is zero-padded host-side to the target's rung so
        ONE executable per slab geometry serves every (slot, pos)
        pair — `slot` is traced, and the stale-tail argument from
        `prefill_slab` makes the zero padding exact: decode overwrites
        position p before any query attends it. Requires the target
        rung to cover `pos` (serve sizes the rung from the session's
        own prompt+budget, which migration preserves). A QUANTIZED
        slab takes the PACKED pair `export_slab_rows` produced —
        (payload, scale) — and transplants both planes; mixing forms
        (packed rows into an fp32 slab or vice versa) raises."""
        import jax
        import jax.numpy as jnp

        L = len(slab)
        qslab = quant_mod.is_quant_cache(slab)
        qrows = isinstance(rows, tuple)
        if qslab != qrows:
            raise ValueError(
                f"KV form mismatch: slab is "
                f"{'int8-packed' if qslab else 'fp32'} but rows are "
                f"{'int8-packed' if qrows else 'fp32'} — the quant "
                "mode must match across a migration (it rides the "
                "fleet spec and knob_fingerprint)")
        c0 = slab[0][0] if qslab else slab[0]
        H, Ts, D = (int(c0.shape[2]), int(c0.shape[3]),
                    int(c0.shape[4]))
        pay = rows[0] if qslab else rows
        t = int(pay.shape[3])
        if pay.shape[0] != L or pay.shape[2] != H \
                or pay.shape[4] != D or t > Ts:
            raise ValueError(
                f"KV rows {tuple(pay.shape)} do not fit slab "
                f"[L={L}, H={H}, T={Ts}, D={D}]")
        cache_dict = self._program_cache()
        key_ = ("import_slab", quant_mod.cache_sig(slab))
        fn = cache_dict.get(key_)
        if fn is None:
            if qslab:
                fn = jax.jit(lambda sl, r, s: [
                    (sl[li][0].at[:, s, :, :, :].set(r[0][li]),
                     sl[li][1].at[:, s, :].set(r[1][li]))
                    for li in range(L)])
            else:
                fn = jax.jit(lambda sl, r, s: [
                    sl[li].at[:, s, :, :, :].set(r[li])
                    for li in range(L)])
            cache_dict[key_] = fn
        if qslab:
            sc = rows[1]
            ppay = np.zeros((L, 2, H, Ts, D), np.int8)
            ppay[:, :, :, :t, :] = pay
            psc = np.zeros((L, 2, Ts), np.float32)
            psc[:, :, :t] = sc
            return fn(list(slab), (ppay, psc), np.int32(slot))
        dt = np.asarray(slab[0]).dtype
        padded = np.zeros((L, 2, H, Ts, D), dt)
        padded[:, :, :, :t, :] = rows
        return fn(list(slab), padded, np.int32(slot))

    def sample_fn(self, temperature, top_k):
        """The EXACT sampling program generate() compiles (argmax when
        temperature == 0, else temperature-scaled top-k categorical)
        as a standalone jitted fn `(logits [B, V], key) -> tok [B]`.
        The serving tier samples each session host-side with the same
        `jax.random.split` sequence generate() traces, keeping
        streamed tokens bit-identical to the sequential path."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        key_ = ("sample", float(temperature), int(top_k),
                autograd._policy_key())
        cache_dict = self._program_cache()
        fn = cache_dict.get(key_)
        if fn is not None:
            return fn

        def sample(logits, key):
            if temperature == 0.0:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            z = logits / temperature
            if top_k > 0:
                k = min(int(top_k), int(logits.shape[-1]))
                kth = lax.top_k(z, k)[0][..., -1:]
                z = jnp.where(z < kth, -jnp.inf, z)
            return jax.random.categorical(key, z).astype(jnp.int32)

        fn = jax.jit(sample)
        cache_dict[key_] = fn
        return fn

    def _shard_decode_params(self, params, mesh):
        """Lay the decode params out for tensor-parallel inference on
        `mesh` ("model" axis): q/k/v and fc1 column-parallel, o and
        fc2 row-parallel, head column-parallel over vocab —
        Megatron's split (parallel/sharding.py). GSPMD then partitions
        the whole prefill+scan program, inserting the collectives."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.sharding import _validate

        def put(x, spec):
            # _validate degrades to replicated when the mesh lacks the
            # axis or the axis size doesn't divide the dim — same
            # fallback the training-path ShardingRules applies
            spec = _validate(mesh, spec, x.shape)
            return jax.device_put(x, NamedSharding(mesh, spec))

        col, row, rep = P(None, "model"), P("model", None), P()

        def norm_put(t):  # replicate array leaves, pass tags/eps through
            return tuple(put(v, rep) if hasattr(v, "shape") else v
                         for v in t)

        def lin(wb, spec):
            w, b = wb
            bspec = (P("model") if spec is col else P())
            return (put(w, spec), None if b is None else put(b, bspec))

        out = {"embed": put(params["embed"], rep),
               "pos": put(params["pos"], rep),
               "ln_f": norm_put(params["ln_f"]),
               "head": put(params["head"], col), "blocks": []}
        for blk in params["blocks"]:
            out["blocks"].append({
                "ln1": norm_put(blk["ln1"]),
                "q": lin(blk["q"], col), "k": lin(blk["k"], col),
                "v": lin(blk["v"], col), "o": lin(blk["o"], row),
                "ln2": norm_put(blk["ln2"]),
                "fc1": lin(blk["fc1"], col), "fc2": lin(blk["fc2"], row),
            })
        return out

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 mesh=None):
        """Autoregressively extend `prompt_ids` [B, P] (numpy int) by
        `max_new_tokens`. temperature=0 → greedy; otherwise softmax
        sampling, optionally truncated to the `top_k` highest logits
        (clamped to the vocab size). The prefill + lax.scan decode
        loop is compiled once per (shape, sampling config) and cached
        on the model. With `mesh` (a jax Mesh with a "model" axis) the
        params are laid out Megatron-style and GSPMD partitions the
        decode across the chips (tensor-parallel inference).

        Precision: decode computes in the PARAM dtype under the
        matmul-precision policy (`tensor.set_matmul_precision` — use
        "default" for bf16 MXU passes, the main inference speed
        lever); the AMP compute-dtype policy is a training-path
        activation policy and is deliberately not applied here, so
        greedy decode stays exactly consistent with the fp32 eval
        forward. Returns numpy [B, P + max_new_tokens]."""
        import jax
        import jax.numpy as jnp

        prompt_ids = np.asarray(prompt_ids, np.int32)
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, "
                             f"got {max_new_tokens}")
        if max_new_tokens == 0:
            return prompt_ids.copy()
        B, P = prompt_ids.shape
        T = P + max_new_tokens
        if T > self.max_len:
            raise ValueError(f"P+new = {T} exceeds max_len {self.max_len}")
        params = self._decode_params()
        if mesh is not None:
            # memoized per mesh: re-putting the whole tree per call
            # would pay a full-model reshard each generate(). Keyed on
            # the live leaf identities so a training step between
            # decodes invalidates the copy (stale weights otherwise).
            shard_cache = getattr(self, "_gen_shard_cache", None)
            if shard_cache is None:
                shard_cache = self._gen_shard_cache = {}
            leaf_ids = tuple(id(l) for l in
                             jax.tree_util.tree_leaves(params))
            hit = shard_cache.get(id(mesh))
            if hit is None or hit[0] != leaf_ids:
                shard_cache[id(mesh)] = (
                    leaf_ids, self._shard_decode_params(params, mesh))
            params = shard_cache[id(mesh)][1]
        L = len(params["blocks"])
        H = self.blocks._seq[0].attn.num_heads
        D = params["embed"].shape[-1] // H
        # cache seq dim rounded up to a power of two, NOT the exact
        # T = P + max_new: pow2 reduction widths are mutually bitwise
        # stable on XLA CPU (trailing masked slots contribute exact
        # zeros in identical lane order), which is what lets the
        # serving tier's shared decode slab (any pow2 >= T) reproduce
        # generate()'s streams bit-for-bit. Odd widths vectorize with
        # a remainder tail and drift in the last ulp. Not max_len:
        # every decode step still attends only ~T slots.
        t_alloc = 1 << (T - 1).bit_length()
        cache = jnp.zeros((L, 2, B, H, t_alloc, D),
                          params["embed"].dtype)
        run = self._compiled_decode(B, P, max_new_tokens, temperature,
                                    top_k)
        new = np.asarray(run(params, jnp.asarray(prompt_ids), cache,
                             jax.random.PRNGKey(seed)))
        return np.concatenate([prompt_ids, new], axis=1)


def create_model(vocab_size=256, **kwargs):
    return TransformerLM(vocab_size, **kwargs)
