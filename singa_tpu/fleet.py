"""Fleet serving: a health-aware router over N serving replicas
(ISSUE 11; ROADMAP item 2(a) — the fleet leg of "millions of users").

PR 8 made a single `ServingEngine` survive poison inputs, hung
dispatches, and dispatcher death. A fleet's failure modes live one
level up: a whole REPLICA dies, a replica's health snapshot goes
stale while it wedges, a shed storm on one replica starves callers
that another replica could have served. `FleetRouter` owns that
level, built on the primitives PR 8 already made fleet-shaped —
`health()` snapshots, structured `ServeOverloadError.retry_after_ms`,
terminal-outcome reconciliation, and the shared prewarmed
export-cache store that makes a new replica deserialize-only at cold
start (the portable-compiled-artifact lesson of PHAST, arxiv
2005.13076):

  routing    — every request goes to the LEAST-LOADED replica whose
      fresh health snapshot says `ready` (`degraded` replicas serve
      only when nothing is ready — still alive, but under pressure);
      `unhealthy` replicas and replicas whose snapshot is older than
      `health_max_age_s` are EJECTED from rotation (a wedged process
      stops writing transitions, so a stale READY must not route) and
      probed for rejoin with seed-jittered exponential backoff.
  failover   — a request whose replica fails it (`ServeDispatchError`
      after the engine's own retries, or `ServeClosedError` from a
      replica dying with the request queued) is re-submitted to a
      DIFFERENT replica, up to `max_failover_hops` hops, each counted.
      A `ServePoisonedError` NEVER fails over: the bisection verdict
      says the input itself is bad, and re-submitting would poison
      every replica in turn.
  shed-aware retry — a replica shedding load refuses with
      `retry_after_ms`; the router first tries the OTHER replicas
      (that is what a fleet is for), and only when everything in
      rotation sheds does it honor the smallest hint — scaled by the
      deterministic seed-keyed jitter of `resilience.backoff_delay_s`
      so a fleet of routers never re-arrives in lockstep — up to
      `max_shed_retries` rounds before the overload propagates.
  drain      — `drain(name)` takes a replica out of rotation, lets
      its in-flight dispatch finish, and REROUTES its queued requests
      through the failover path (their futures fail `ServeClosedError`
      on the drained replica; the router re-submits elsewhere) — a
      rolling restart loses nothing.
  supervision — a fleet supervisor thread restarts dead (killed)
      replicas, bounded by `max_restarts` per replica; with the
      shared export-cache store armed the restarted replica's model
      is fresh (nothing cached in-process) yet its first dispatch is
      deserialize-only: store hits >= 1, traces == 0.
  chaos      — `FleetRouter(..., fault_injector=...)` consumes the
      fleet-level `resilience.FaultInjector` kinds keyed by the
      router submit ordinal: `replica_kill` (hard-kill the replica
      the request just routed to), `replica_hang` (its next dispatch
      sleeps `hang_s`), `stale_health` (its health snapshot freezes,
      aging into ejection). The soak in `tests/test_fleet.py` proves
      availability stays bounded, replies stay bit-identical to the
      unbatched forward, and the reconciliation below holds exactly.

Zero silent loss, fleet-wide: three equations, all EXACT at
quiescence (every returned future resolved), checked by
`fleet.reconcile`:

  engine terminals   serve.requests == replies + expired + shed +
                     dropped + overflowed + failed      (per PR 8)
  routing            serve.requests == fleet.routed + fleet.failovers
                     + fleet.refused   (every engine submit the
                     router made lands in exactly one bucket)
  router terminals   fleet.requests == fleet.replies + fleet.failed
                     + fleet.rejected  (every router future resolves
                     into exactly one terminal bucket)

Fleet decode serving (ISSUE 17): `submit_decode` routes generative
sessions with SESSION AFFINITY (sticky by session_id while the sticky
replica has a free KV slot) over occupancy-aware placement (most free
KV slots from the same health surface heartbeats ship, ties by least
depth). Each session's `FleetDecodeReply` is a stream proxy whose pump
thread survives the replica underneath changing: `drain(name)`
checkpoints live sessions (`export_decode_sessions` -> the PR 13 wire's
MIGRATE frame) and the proxy resumes each checkpoint on another
replica mid-stream, bit-identically (KV transplant); a SIGKILLed
replica's sessions re-prefill from the proxy's delivered token ledger
(correctness first — migration is only the fast path). Never a torn
or duplicated token: resumed streams re-play the ledger prefix and
the proxy verifies it against what it already delivered. The PR 16
session equation (sessions == completed + failed + expired + shed)
joins `reconcile` fleet-wide via the decode0/decode1 snapshots.

`Replica` is a small duck-typed protocol (start/kill/restart/submit/
health/depth/...) so a later multi-process transport slots in without
touching the routing logic; `EngineReplica` is the in-process
implementation over one `ServingEngine`.

Observability: `cache_stats()["fleet"]` (counters + per-replica
state), `route`/`failover` spans through the PR 5 tracer, and a
fleet metrics JSONL (one record per state transition plus every
`metrics_every` routes). Knobs: `device.set_fleet(...)`.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import export_cache, slo as slo_mod, stats as stats_mod, \
    trace as trace_mod
from .serve import (
    ServeClosedError,
    ServeDeadlineError,
    ServeDispatchError,
    ServeMigratedError,
    ServeOverloadError,
    ServePoisonedError,
    ServeQueueFullError,
    ServingEngine,
)

__all__ = [
    "FleetRouter",
    "FleetReply",
    "FleetDecodeReply",
    "EngineReplica",
    "FleetUnavailableError",
    "configure",
    "get_config",
    "make_replicas",
    "reconcile",
    "reconcile_transport",
]


class FleetUnavailableError(RuntimeError):
    """No replica in rotation could accept the request: everything is
    ejected/dead/draining, or every live replica refused (queue full)
    and the shed-retry budget is spent. Counted `rejected` — the
    router-terminal analogue of the engine's loud queue-full drop."""


# ---------------------------------------------------------------------------
# Process-default knobs (user-facing setter: device.set_fleet).
# ---------------------------------------------------------------------------
_CONFIG: Dict = {
    # Failover re-submits per request after a replica fails it.
    # 0 = a replica failure is terminal (single-engine semantics).
    "max_failover_hops": 2,
    # Rounds of honor-the-hint waiting when EVERY replica in rotation
    # sheds (trying a different replica costs no wait and comes first).
    "max_shed_retries": 2,
    # Cap on one shed wait (retry_after_ms is an estimate; a wild one
    # must not park the caller for minutes).
    "max_shed_sleep_s": 1.0,
    # Health snapshot age beyond which a replica counts as stale =>
    # ejected (a wedged writer stops refreshing; fail closed).
    "health_max_age_s": 5.0,
    # Base backoff between rejoin probes of an ejected replica
    # (doubles per failed probe, seed-jittered).
    "probe_backoff_ms": 50.0,
    # Supervisor restarts per DEAD replica before it is abandoned
    # ("failed" state, permanently out of rotation).
    "max_restarts": 3,
    # Supervisor sweep period (restart/rejoin latency floor).
    "supervise_interval_s": 0.02,
    # Emit a fleet metrics record every N routed requests (state
    # transitions always log). 0 = transitions only.
    "metrics_every": 32,
    # --- multi-process transport (ISSUE 13; singa_tpu.fleet_proc) ---
    # Replica transport `make_replicas` builds: "engine" (in-process
    # EngineReplica, PR 11) or "proc" (worker subprocess behind the
    # same Replica protocol).
    "transport": "engine",
    # Per-message IPC bound: an admission ACK (or, past the request's
    # own deadline, a reply frame) later than this fails the caller
    # with a structured ProcTransportError => failover.
    "ipc_deadline_ms": 10000.0,
    # Worker heartbeat period. A missed heartbeat ages the health
    # snapshot into the router's stale ejection (fail closed), so
    # keep health_max_age_s a few multiples above this.
    "heartbeat_interval_s": 0.25,
    # Bound on worker spawn -> HELLO (the respawn path shares it).
    "spawn_timeout_s": 120.0,
    # Max in-flight requests per worker before the parent sheds with
    # retry_after_ms instead of ballooning the pipe.
    "max_inflight": 256,
    # --- TCP transport (ISSUE 18; transport="tcp") ---
    # How long a lost connection keeps its worker generation ALIVE
    # awaiting an authenticated same-fence reconnect before the
    # supervisor declares it dead and restarts. In-flight requests
    # fail over immediately either way — the window trades restart
    # churn against blips, never availability.
    "reconnect_window_s": 10.0,
    # Reader-side bound on a single frame's claimed payload size: a
    # corrupt/hostile length prefix fails the connection loudly
    # (FrameCorruptError) instead of ballooning RSS.
    "max_frame_bytes": 256 * 1024 * 1024,
}


def configure(**kw) -> Dict:
    """Update fleet-router defaults. User-facing setter:
    `device.set_fleet`."""
    for k, v in kw.items():
        if k not in _CONFIG:
            raise KeyError(f"unknown fleet config key {k!r}; known: "
                           f"{sorted(_CONFIG)}")
        if k == "transport":
            v = str(v)
            if v not in ("engine", "proc", "tcp"):
                raise ValueError(
                    "transport must be 'engine', 'proc', or 'tcp', "
                    f"got {v!r}")
        elif k == "max_frame_bytes":
            v = int(v)
            if v < 1024:
                raise ValueError("max_frame_bytes must be >= 1024")
        elif k in ("max_failover_hops", "max_shed_retries",
                   "max_restarts", "metrics_every"):
            v = int(v)
            if v < 0:
                raise ValueError(f"{k} must be >= 0")
        elif k == "max_inflight":
            v = int(v)
            if v < 1:
                raise ValueError("max_inflight must be >= 1")
        else:
            v = float(v)
            if v <= 0:
                raise ValueError(f"{k} must be > 0")
        _CONFIG[k] = v
    return dict(_CONFIG)


def get_config() -> Dict:
    return dict(_CONFIG)


# ---------------------------------------------------------------------------
# Observability: cache_stats()["fleet"]
# ---------------------------------------------------------------------------
class _FleetStats:
    """Fleet counters. Three families, mirroring the reconciliation
    equations in the module docstring: router terminals
    (requests/replies/failed/rejected), engine-submit attempts
    (routed/failovers/refused), and rotation events
    (ejections/rejoins/restarts/probes + the chaos injection tallies).
    `per_replica` in the snapshot is LIVE state assembled from the
    routers alive right now."""

    def __init__(self):
        self._routers: "weakref.WeakSet[FleetRouter]" = weakref.WeakSet()
        self.reset()

    def reset(self) -> None:
        # router terminals
        self.requests = 0
        self.replies = 0
        self.failed = 0
        self.rejected = 0
        # engine-submit attempts
        self.routed = 0
        self.failovers = 0
        self.refused = 0
        self.shed_retries = 0
        # decode-tier sessions (ISSUE 17): router terminals mirror the
        # forward family (every FleetDecodeReply resolves into exactly
        # one of decode_replies/decode_failed; a submit_decode that
        # never produced a future counts decode_rejected), and the
        # placement attempts split by WHY the session moved —
        # decode_routed (fresh placements), decode_migrations (planned
        # checkpoint hand-offs: drain shipped a `ServeMigratedError`
        # and the stream proxy resumed it elsewhere), decode_replays
        # (unplanned: the replica died mid-stream and the proxy
        # re-prefilled from its delivered token ledger on another one)
        self.decode_requests = 0
        self.decode_replies = 0
        self.decode_failed = 0
        self.decode_rejected = 0
        self.decode_routed = 0
        self.decode_migrations = 0
        self.decode_replays = 0
        self.decode_refused = 0
        self.decode_shed_retries = 0
        # rotation events
        self.ejections = 0
        self.rejoins = 0
        self.restarts = 0
        self.probes = 0
        self.drains = 0
        # chaos injections (fleet-level kinds that actually fired;
        # proc_sigkill counts into kills_injected — a kill is a kill)
        self.kills_injected = 0
        self.hangs_injected = 0
        self.stale_injected = 0
        self.pipe_stalls_injected = 0
        self.torn_frames_injected = 0
        # network-fault injections (ISSUE 18): tcp transport only —
        # faults that fired through a replica's ChaosProxy
        self.net_faults_injected = 0
        self.net_partitions_injected = 0

    def snapshot(self) -> Dict:
        per: Dict[str, Dict] = {}
        for router in list(self._routers):
            per.update(router.replica_snapshot())
        return {
            "requests": self.requests,
            "replies": self.replies,
            "failed": self.failed,
            "rejected": self.rejected,
            "routed": self.routed,
            "failovers": self.failovers,
            "refused": self.refused,
            "shed_retries": self.shed_retries,
            "decode_requests": self.decode_requests,
            "decode_replies": self.decode_replies,
            "decode_failed": self.decode_failed,
            "decode_rejected": self.decode_rejected,
            "decode_routed": self.decode_routed,
            "decode_migrations": self.decode_migrations,
            "decode_replays": self.decode_replays,
            "decode_refused": self.decode_refused,
            "decode_shed_retries": self.decode_shed_retries,
            "ejections": self.ejections,
            "rejoins": self.rejoins,
            "restarts": self.restarts,
            "probes": self.probes,
            "drains": self.drains,
            "kills_injected": self.kills_injected,
            "hangs_injected": self.hangs_injected,
            "stale_injected": self.stale_injected,
            "pipe_stalls_injected": self.pipe_stalls_injected,
            "torn_frames_injected": self.torn_frames_injected,
            "net_faults_injected": self.net_faults_injected,
            "net_partitions_injected": self.net_partitions_injected,
            "per_replica": per,
        }


_STATS = _FleetStats()
stats_mod.register_cache("fleet", _STATS)


def fleet_stats() -> _FleetStats:
    return _STATS


def reconcile(serve0: Dict, serve1: Dict, fleet0: Dict,
              fleet1: Dict, replicas: Optional[Sequence] = None,
              decode0: Optional[Dict] = None,
              decode1: Optional[Dict] = None) -> Dict:
    """Check the three zero-silent-loss equations over a
    (before, after) window of `cache_stats()["serve"]` /
    `cache_stats()["fleet"]` snapshots. Exact integer equality — one
    lost future anywhere breaks one of them. Returns the per-equation
    booleans, the combined `ok`, and the deltas for the failure
    message.

    For a multi-process fleet the parent MIRRORS every IPC request
    into its own serve counters (`serve.note_remote_request` /
    `note_remote_terminal`), so the same three equations hold across
    the process boundary unchanged. Pass `replicas` (the fleet's
    `ProcReplica` handles) to ALSO check the transport ledger —
    `reconcile_transport` — and fold its verdict into `ok`: every
    admitted request either produced a frame that arrived or was
    swept into `failed` when its worker generation died (a
    killed-in-flight request can land in failover, never vanish).

    Pass `decode0`/`decode1` (`cache_stats()["decode"]` snapshots) to
    ALSO check the decode tier fleet-wide (ISSUE 17). Two more exact
    equations join the report:

      decode sessions    sessions == completed + failed + expired +
                         shed  (the PR 16 per-engine equation — the
                         parent mirrors every remote session, exports
                         net to zero once the session resumes, so the
                         SAME equation holds across the whole fleet at
                         quiescence, SIGKILLs and migrations included)
      decode terminals   fleet.decode_requests == decode_replies +
                         decode_failed + decode_rejected (every
                         `FleetDecodeReply` resolves exactly once)
    """
    sd = {k: serve1[k] - serve0[k] for k in
          ("requests", "replies", "expired", "shed", "dropped",
           "overflowed", "failed")}
    fd = {k: fleet1[k] - fleet0[k] for k in
          ("requests", "replies", "failed", "rejected", "routed",
           "failovers", "refused")}
    fdd = {k: fleet1.get(k, 0) - fleet0.get(k, 0) for k in
           ("decode_requests", "decode_replies", "decode_failed",
            "decode_rejected", "decode_routed", "decode_migrations",
            "decode_replays", "decode_refused")}
    engine_ok = sd["requests"] == (sd["replies"] + sd["expired"]
                                   + sd["shed"] + sd["dropped"]
                                   + sd["overflowed"] + sd["failed"])
    routing_ok = sd["requests"] == (fd["routed"] + fd["failovers"]
                                    + fd["refused"])
    router_ok = fd["requests"] == (fd["replies"] + fd["failed"]
                                   + fd["rejected"])
    out = {
        "ok": bool(engine_ok and routing_ok and router_ok),
        "engine_terminals": bool(engine_ok),
        "routing": bool(routing_ok),
        "router_terminals": bool(router_ok),
        "serve_delta": sd,
        "fleet_delta": fd,
    }
    decode_router_ok = fdd["decode_requests"] == (
        fdd["decode_replies"] + fdd["decode_failed"]
        + fdd["decode_rejected"])
    out["decode_router_terminals"] = bool(decode_router_ok)
    out["fleet_decode_delta"] = fdd
    out["ok"] = bool(out["ok"] and decode_router_ok)
    if decode0 is not None and decode1 is not None:
        dd = {k: decode1[k] - decode0[k] for k in
              ("sessions", "completed", "failed", "expired", "shed",
               "migrated", "resumed")}
        decode_sessions_ok = dd["sessions"] == (
            dd["completed"] + dd["failed"] + dd["expired"]
            + dd["shed"])
        out["decode_sessions"] = bool(decode_sessions_ok)
        out["decode_delta"] = dd
        out["ok"] = bool(out["ok"] and decode_sessions_ok)
    if replicas is not None:
        tr = reconcile_transport(replicas)
        out["transport"] = tr["ok"]
        out["transport_detail"] = tr
        out["ok"] = bool(out["ok"] and tr["ok"])
    return out


def reconcile_transport(replicas: Sequence) -> Dict:
    """The process-boundary ledger (ISSUE 13), exact at quiescence,
    per replica and per worker GENERATION:

      parent terminals   sent == delivered + err_replies +
                         transport_failed  (every admitted IPC
                         request resolved into exactly one parent-side
                         outcome; pending must be 0), and the decode
                         LANE likewise: decode_sent ==
                         decode_delivered + decode_err_replies +
                         decode_transport_failed + migrated_out (a
                         migrated session is an outcome too — it left
                         on a MIGRATE frame to resume elsewhere)
      generation ledger  admitted == frames + swept + migrated (every
                         admitted request either produced a
                         reply/error frame that arrived, was swept
                         into `failed` when its generation died — the
                         kill-time accounting — or left on a MIGRATE
                         frame)
      worker handshake   for generations that drained CLEANLY (the
                         BYE frame carries the worker's final
                         counters): the worker's own engine-terminal
                         equation holds on the shipped snapshot — and
                         when the handshake carries decode-session
                         books, the 4-equation decode reconciliation
                         (sessions == completed + failed + expired +
                         shed) holds on them too — the cross-process
                         proof that the worker lost nothing
                         internally either.

    Replicas without a `transport_snapshot` (in-process
    `EngineReplica`s) are skipped — their accounting is already the
    shared-process serve counters."""
    per: Dict[str, Dict] = {}
    ok = True
    for r in replicas:
        snap_fn = getattr(r, "transport_snapshot", None)
        if snap_fn is None:
            continue
        t = snap_fn()
        dec = t.get("decode") or {}
        parent_ok = (t["pending"] == 0
                     and t["sent"] == (t["delivered"] + t["err_replies"]
                                       + t["transport_failed"])
                     and dec.get("sent", 0) == (
                         dec.get("delivered", 0)
                         + dec.get("err_replies", 0)
                         + dec.get("transport_failed", 0)
                         + dec.get("migrated_out", 0)))
        gens_ok = True
        hands_ok = True
        for g, gen in t["generations"].items():
            if gen["admitted"] != (gen["frames"] + gen["swept"]
                                   + gen.get("migrated", 0)):
                gens_ok = False
            h = gen["handshake"]
            if gen["clean"] and h:
                wt = h["terminal"]
                if wt["requests"] != (wt["replies"] + wt["expired"]
                                      + wt["shed"] + wt["dropped"]
                                      + wt["overflowed"]
                                      + wt["failed"]):
                    hands_ok = False
                wd = h.get("decode")
                if wd and wd["sessions"] != (
                        wd["completed"] + wd["failed"]
                        + wd["expired"] + wd["shed"]):
                    hands_ok = False
        r_ok = bool(parent_ok and gens_ok and hands_ok)
        per[r.name] = {"ok": r_ok, "parent_terminals": bool(parent_ok),
                       "generations": bool(gens_ok),
                       "handshakes": bool(hands_ok), "snapshot": t}
        ok = ok and r_ok
    return {"ok": bool(ok), "per_replica": per}


def make_replicas(n: int, spec: Dict, transport: Optional[str] = None,
                  engine_kwargs: Optional[Dict] = None,
                  name_prefix: str = "r", **proc_kwargs) -> List:
    """Spec-based replica factory: build N replicas of the configured
    `transport` ("engine" = in-process `EngineReplica`, "proc" = one
    worker subprocess each via `fleet_proc.ProcReplica`; default: the
    `device.set_fleet(transport=...)` knob) from ONE deterministic
    spec — {"factory": "module:callable", "factory_kwargs": {...},
    "sys_path": [...], ...} (the `fleet_proc.ProcReplica` spec shape).
    Replica `i` gets `device_index=i` merged into its factory kwargs,
    so an N-chip host spreads the fleet one-per-chip and the shared-
    device warning fires when two replicas collide on one."""
    transport = transport or get_config()["transport"]
    out: List = []
    for i in range(int(n)):
        s = dict(spec)
        fk = dict(s.get("factory_kwargs") or {})
        fk.setdefault("device_index", i)
        s["factory_kwargs"] = fk
        name = f"{name_prefix}{i}"
        if s.get("metrics_dir"):
            # one JSONL per WORKER process: N processes appending to
            # one file would interleave mid-record
            import os as _os

            s["metrics_path"] = _os.path.join(
                s.pop("metrics_dir"), f"{name}.worker.jsonl")
        if s.get("health_dir"):
            # per-replica health snapshots in one directory — the
            # `tools/serve_health.py --all` fleet-probe layout
            import os as _os

            ekw = dict(s.get("engine") or {})
            ekw["health_file"] = _os.path.join(
                s.pop("health_dir"), f"{name}.health.json")
            s["engine"] = ekw
        if transport in ("proc", "tcp"):
            from .fleet_proc import ProcReplica

            if engine_kwargs:
                ekw = dict(s.get("engine") or {})
                ekw.update(engine_kwargs)
                s["engine"] = ekw
            pk = dict(proc_kwargs)
            if transport == "tcp":
                # listen mode: the parent binds a routable host:port
                # (ephemeral loopback by default — hermetic) and the
                # worker is launched with ONLY the remote-recipe CLI
                # (--connect host:port --token). A "net_chaos" spec
                # entry arms the deterministic ChaosProxy between
                # them (singa_tpu.netchaos).
                pk.setdefault("mode", "listen")
                if s.get("net_chaos") is not None:
                    pk.setdefault("net_chaos",
                                  dict(s.pop("net_chaos")))
                else:
                    s.pop("net_chaos", None)
            out.append(ProcReplica(name, s, **pk))
            continue
        if transport != "engine":
            raise ValueError(
                f"unknown fleet transport {transport!r} "
                "(engine|proc|tcp)")
        from .fleet_proc import resolve_factory

        fn = resolve_factory(s)

        def factory(fn=fn, fk=fk):
            return fn(**fk)

        ekw = dict(s.get("engine") or {})
        if engine_kwargs:
            ekw.update(engine_kwargs)
        # One spec, either transport: the worker-side extras the proc
        # spec names must not silently vanish in-process — a "chaos"
        # fleet whose injector was dropped would exercise nothing.
        if s.get("injector"):
            from .resilience import FaultInjector

            ij = s["injector"]
            ekw.setdefault("fault_injector", FaultInjector(
                seed=int(ij.get("seed", 0)),
                schedule=ij.get("schedule") or {},
                hang_s=float(ij.get("hang_s", 0.05))))
        if s.get("metrics_path"):
            ekw.setdefault(
                "metrics", trace_mod.MetricsLogger(s["metrics_path"]))
        # export_cache/buckets are PROCESS-level state in-process:
        # the engine transport reads the knobs already armed via
        # device.set_export_cache / set_shape_buckets.
        out.append(EngineReplica(name, factory, ekw))
    return out


# ---------------------------------------------------------------------------
# Replica protocol + the in-process implementation
# ---------------------------------------------------------------------------
class EngineReplica:
    """One in-process serving replica: a `ServingEngine` built from a
    `model_factory` so `restart()` can rebuild the MODEL too — a
    restarted replica holds nothing in process memory, which is what
    makes the deserialize-only cold start from the shared export-cache
    store provable (store hits, zero traces) rather than an artifact
    of a still-warm `_JitForward`.

    This class IS the `Replica` protocol a future multi-process
    transport reimplements (the router calls nothing else):

      start() / kill() / drain_stop() / restart()
      submit(*arrays, deadline_ms=...) -> ServeReply-like future
      health() -> dict with "state" and a wall-clock "time" stamp
                  (no/old "time" reads as stale => ejected; fail
                  closed, like tools/serve_health.py)
      depth() -> queued requests right now (the load signal)
      warmup(*arrays), killed (bool attr)

    plus the chaos hooks the fleet FaultInjector kinds drive:
    `hang_once(s)` and `freeze_health(s)`.

    `model_factory` must be deterministic (same params every call) if
    the fleet's bit-identity guarantees are to survive a restart —
    seed it, or close over a checkpoint path. It must also build the
    model on its OWN device (`device.create_tpu_device()`), not the
    shared process default: a fleet runs N dispatcher threads, and
    the per-device RNG key (`dev._rng_key`) is single-writer state —
    two replicas tracing on one shared device object race it (a
    leaked tracer poisons whichever dispatch reads mid-trace). The
    router warns loudly at `start()` when replicas share a device.
    """

    def __init__(self, name: str, model_factory,
                 engine_kwargs: Optional[Dict] = None):
        self.name = str(name)
        self._factory = model_factory
        self._kwargs = dict(engine_kwargs or {})
        self.engine: Optional[ServingEngine] = None
        self.killed = False
        self.restarts = 0
        self._frozen_snap: Optional[Dict] = None
        self._frozen_until = 0.0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "EngineReplica":
        if self.engine is None:
            self.engine = ServingEngine(self._factory(), **self._kwargs)
        self.engine.start()
        self.killed = False
        return self

    def kill(self) -> None:
        """Hard replica death: the queue fails loudly
        (`ServeClosedError` — the router's failover reroutes those
        futures), the current in-flight dispatch is given a short
        bound to finish, and the replica stays dead until
        `restart()`. The in-process stand-in for a killed worker
        process whose router tier detects the death."""
        self.killed = True
        eng = self.engine
        if eng is not None:
            eng.stop(drain=False, drain_timeout_s=0.5)

    def drain_stop(self) -> None:
        """Drain semantics for the router: stop admitting, let the
        in-flight dispatch finish, fail the still-queued futures so
        the router reroutes them (`ServeClosedError` -> failover).
        Live decode sessions are CHECKPOINTED first
        (`export_decode_sessions`, ISSUE 17): each one's reply fails
        `ServeMigratedError` carrying the portable checkpoint, which
        the fleet stream proxy resumes on another replica with zero
        token loss — a drain migrates sessions, it never kills them."""
        eng = self.engine
        if eng is not None:
            try:
                eng.export_decode_sessions()
            except Exception:
                # export is the FAST path only: if it fails, stop()
                # fails the sessions `ServeClosedError` and the proxy
                # replays each from its delivered token ledger
                pass
            eng.stop(drain=False, drain_timeout_s=1.0)

    def restart(self) -> "EngineReplica":
        """Fresh model + fresh engine (the old one is torn down if it
        still runs). With the shared export-cache store armed and
        prewarmed, the new engine's first dispatch of every bucket is
        a store LOAD — deserialize-only cold start."""
        old, self.engine = self.engine, None
        if old is not None:
            try:
                old.stop(drain=False, drain_timeout_s=0.2)
            except Exception:
                pass
        self.restarts += 1
        self._frozen_snap = None
        return self.start()

    def stop(self, drain: bool = True) -> None:
        eng = self.engine
        if eng is not None:
            eng.stop(drain=drain)

    # -- request path -----------------------------------------------------
    def submit(self, *arrays, deadline_ms: Optional[float] = None):
        eng = self.engine
        if eng is None or self.killed:
            raise ServeClosedError(f"replica {self.name} is dead")
        return eng.submit(*arrays, deadline_ms=deadline_ms)

    def warmup(self, *arrays) -> int:
        eng = self.engine
        if eng is None:
            raise ServeClosedError(f"replica {self.name} not started")
        return eng.warmup(*arrays)

    # -- decode tier (ISSUE 17) -------------------------------------------
    def submit_decode(self, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      seed: int = 0,
                      deadline_ms: Optional[float] = None):
        eng = self.engine
        if eng is None or self.killed:
            raise ServeClosedError(f"replica {self.name} is dead")
        return eng.submit_decode(prompt_ids, max_new_tokens,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed, deadline_ms=deadline_ms)

    def resume_decode(self, ckpt: Dict):
        eng = self.engine
        if eng is None or self.killed:
            raise ServeClosedError(f"replica {self.name} is dead")
        return eng.resume_decode(ckpt)

    def warm_decode(self, prompt_lens=(), max_new_tokens=None,
                    samplers=()) -> int:
        eng = self.engine
        if eng is None:
            raise ServeClosedError(f"replica {self.name} not started")
        return eng.warm_decode(prompt_lens, max_new_tokens,
                               samplers=samplers)

    # -- health/load signals ----------------------------------------------
    def health(self) -> Dict:
        """Engine health + the wall-clock stamp the router's staleness
        check reads. Under an injected `stale_health` the LAST
        truthful snapshot keeps being returned with its old stamp —
        exactly what a wedged snapshot writer looks like from the
        router's side."""
        if (self._frozen_snap is not None
                and time.perf_counter() < self._frozen_until):
            return dict(self._frozen_snap)
        eng = self.engine
        if eng is None or self.killed:
            snap = {"state": "unhealthy",
                    "reasons": [f"replica {self.name} is dead"]}
        else:
            snap = eng.health()
        snap["time"] = round(time.time(), 3)
        snap["name"] = self.name
        return snap

    def depth(self) -> int:
        eng = self.engine
        if eng is None:
            return 0
        return eng._depth

    def device_token(self):
        """Identity of the device object this replica dispatches on —
        the router's shared-device check (see class docstring)."""
        eng = self.engine
        if eng is None:
            return None
        ps = eng.model.param_tensors()
        return id(ps[0].device) if ps else None

    # -- chaos hooks (fleet FaultInjector kinds) --------------------------
    def hang_once(self, hang_s: float) -> None:
        """`replica_hang`: the replica's NEXT dispatch attempt sleeps
        `hang_s` before proceeding (one-shot, then the hook restores
        itself) — the mid-fleet stall the drain timeout and the
        router's depth signal are supposed to absorb."""
        eng = self.engine
        if eng is None:
            return
        orig = eng._chaos_attempt
        fired: List[int] = []

        def hooked(group):
            if not fired:
                fired.append(1)
                eng._chaos_attempt = orig
                time.sleep(float(hang_s))
            return orig(group)

        eng._chaos_attempt = hooked

    def freeze_health(self, for_s: float) -> None:
        """`stale_health`: freeze the health surface on the current
        snapshot for `for_s` seconds. Its timestamp stops advancing,
        so once `health_max_age_s` passes the router must eject the
        replica no matter what state the frozen snapshot claims."""
        self._frozen_snap = self.health()
        self._frozen_until = time.perf_counter() + float(for_s)


class _ReplicaSlot:
    """Router-side bookkeeping for one replica handle."""

    __slots__ = ("handle", "name", "state", "reason", "routed",
                 "refusals", "failures", "restarts", "probe_attempt",
                 "next_probe_t")

    def __init__(self, handle):
        self.handle = handle
        self.name = handle.name
        self.state = "ready"  # ready|degraded|ejected|dead|draining|stopped|failed
        self.reason = ""
        self.routed = 0
        self.refusals = 0
        self.failures = 0
        self.restarts = 0
        self.probe_attempt = 0
        self.next_probe_t = 0.0

    def in_rotation(self) -> bool:
        return self.state in ("ready", "degraded")


# ---------------------------------------------------------------------------
# The fleet future
# ---------------------------------------------------------------------------
class FleetReply:
    """Future for one fleet request. `result(timeout)` blocks like
    `ServeReply.result` — and performs the failover hops IN the
    caller's wait: when the current replica fails the request
    retryably (`ServeDispatchError`, `ServeClosedError`) and hops
    remain, the router re-submits to a different replica and the wait
    continues. Terminal errors (`ServePoisonedError`,
    `ServeDeadlineError`, exhausted hops, nothing left to route to)
    re-raise. A `TimeoutError` is NOT terminal — call again.

    `replica` names where the request currently lives; `hops` counts
    completed failovers. Exactly one terminal outcome is counted into
    `cache_stats()["fleet"]` (`replies`/`failed`) per future, however
    many threads call `result()`."""

    __slots__ = ("_router", "_arrays", "_deadline_abs", "_inner",
                 "replica", "hops", "_tried", "_lock", "_state_lock",
                 "_terminal", "_error", "t_submit", "t_reply", "trace")

    def __init__(self, router: "FleetRouter", arrays,
                 deadline_abs: Optional[float], inner, replica: str,
                 trace: Optional[str] = None):
        self._router = router
        self._arrays = arrays
        self._deadline_abs = deadline_abs
        self._inner = inner
        self.replica = replica
        self.hops = 0
        # trace_id born at submit (ISSUE 15): failover hops re-submit
        # under the SAME id, so one request's spans — across replicas,
        # across processes — stay one timeline
        self.trace = trace
        self._tried = {replica}
        self._lock = threading.RLock()  # serializes failover work
        self._state_lock = threading.Lock()  # guards terminal counting
        self._terminal = False
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_reply: Optional[float] = None

    def done(self) -> bool:
        """True when `result()` will return/raise without waiting on a
        replica. A retryably-failed inner future reads done until
        `result()` runs the failover, so poll `result(timeout=...)`
        rather than spinning on `done()` when hops matter."""
        return self._terminal or self._inner.done()

    @property
    def state(self) -> str:
        if self._terminal:
            return "failed" if self._error is not None else "done"
        return f"{self._inner.state}@{self.replica}"

    @property
    def latency_s(self) -> Optional[float]:
        return (None if self.t_reply is None
                else self.t_reply - self.t_submit)

    def _finish(self, err: Optional[BaseException]) -> None:
        with self._state_lock:
            if self._terminal:
                return
            self._terminal = True
            self._error = err
            # Latency is submit -> the replica's DELIVERY time when
            # the inner future has one — the caller may observe the
            # result long after the reply landed, and that wait is
            # not serving latency.
            t = getattr(self._inner, "t_reply", None)
            self.t_reply = t if t is not None else time.perf_counter()
        if err is None:
            _STATS.replies += 1
        else:
            _STATS.failed += 1
        # ISSUE 20: one availability event per router terminal —
        # the same ledger the reconciliation equations count
        slo_mod.observe_outcome(err is None)

    def result(self, timeout: Optional[float] = None):
        t_end = (None if timeout is None
                 else time.perf_counter() + timeout)
        with self._lock:
            while True:
                if self._terminal:
                    if self._error is not None:
                        raise self._error
                    return self._inner.result(0.0)
                rem = (None if t_end is None
                       else max(t_end - time.perf_counter(), 0.0))
                inner = self._inner
                try:
                    val = inner.result(rem)
                except TimeoutError:
                    raise  # not terminal: the request is still live
                except (ServePoisonedError, ServeDeadlineError) as e:
                    # poison verdicts and deadline expiries are
                    # terminal BY CONTRACT: re-submitting a poison
                    # input poisons the next replica, and a deadline
                    # the caller set has simply passed
                    self._finish(e)
                    raise
                except (ServeDispatchError, ServeClosedError) as e:
                    if self.hops >= self._router.max_failover_hops:
                        from .resilience import annotate_exception

                        annotate_exception(
                            e, f"fleet: {self.hops} failover hop(s) "
                               f"exhausted (max_failover_hops "
                               f"{self._router.max_failover_hops})")
                        self._finish(e)
                        raise
                    try:
                        self._failover(e)
                    except BaseException as e2:
                        self._finish(e2)
                        raise
                    continue
                except BaseException as e:
                    self._finish(e)
                    raise
                self._finish(None)
                return val

    def _failover(self, err: BaseException) -> None:
        """Re-submit to a different replica (prefer untried ones).
        Raises when the deadline already passed or nothing can accept
        — the caller terminalizes with THAT error."""
        deadline_ms = None
        if self._deadline_abs is not None:
            deadline_ms = (self._deadline_abs
                           - time.perf_counter()) * 1e3
            if deadline_ms <= 0:
                raise ServeDeadlineError(
                    f"deadline passed during failover from "
                    f"{self.replica}: {err!r}")
        t0 = time.perf_counter()
        with trace_mod.context(self.trace):
            inner, name = self._router._route_submit(
                self._arrays, deadline_ms, exclude=set(self._tried),
                failover=True)
        self.hops += 1
        self._tried.add(name)
        self.replica = name
        self._inner = inner
        trace_mod.record_span("failover", t0, time.perf_counter(),
                              trace=self.trace, hop=self.hops,
                              to=name, error=repr(err))


# ---------------------------------------------------------------------------
# The fleet decode stream proxy (ISSUE 17)
# ---------------------------------------------------------------------------
class FleetDecodeReply:
    """Future + token stream for one fleet decode SESSION. The caller
    holds THIS object for the session's whole life; which replica is
    generating underneath changes — planned migration on `drain()`,
    ledger replay after a SIGKILL — without the stream ever tearing,
    duplicating, or going quiet unannounced.

    One pump thread per session transfers the current inner
    `ServeReply`'s tokens into the proxy stream, de-duplicated BY
    COUNT: a resumed inner reply re-plays the ledger prefix first
    (`resume_decode`'s contract), the pump skips tokens it already
    delivered, and every skipped token is ASSERTED equal to what was
    delivered — a checkpoint that diverges from the delivered prefix
    is the exact torn-stream corruption the chaos invariant forbids,
    and it fails the session loudly rather than silently forking it.

    Re-placement, in the pump (never in the caller's wait):

      `ServeMigratedError`  — planned hand-off: the source replica
          drained and shipped a checkpoint; resume it elsewhere (KV
          transplant, the fast path). Does NOT consume the failover
          budget (a drain is an operator action, not a failure), but
          is bounded at `max_failover_hops + fleet size` hand-offs so
          a rolling drain of everything cannot ping-pong forever.
      `ServeDispatchError` / `ServeClosedError`  — the replica died
          mid-stream (SIGKILL): re-prefill from the proxy's OWN
          delivered token ledger (`kv=None` — correctness first,
          migration is only the fast path) on another replica, up to
          `max_failover_hops` hops.
      `ServePoisonedError` / `ServeDeadlineError`  — terminal by
          contract, exactly like the forward tier.

    Exactly one terminal outcome is counted into
    `cache_stats()["fleet"]` (`decode_replies`/`decode_failed`) per
    session. `tokens()` / `result()` match `ServeReply`'s surface; a
    completed session's full sequence is bit-identical to the
    single-engine `generate()` with the same prompt, sampling config
    and seed, however many replicas it crossed."""

    __slots__ = ("_router", "session_id", "_inner", "replica", "hops",
                 "migrations", "_tried", "_params", "_stream",
                 "_stream_cv", "_stream_closed", "_ev", "_value",
                 "_error", "t_submit", "t_reply", "trace", "_pump")

    def __init__(self, router: "FleetRouter", session_id: str, inner,
                 replica: str, trace: Optional[str], params: Dict):
        self._router = router
        self.session_id = session_id
        self._inner = inner
        self.replica = replica
        self.hops = 0          # unplanned re-placements (replays)
        self.migrations = 0    # planned hand-offs (drain checkpoints)
        self._tried = {replica}
        self._params = params
        self._stream: List[int] = []
        self._stream_cv = threading.Condition()
        self._stream_closed = False
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_reply: Optional[float] = None
        self.trace = trace
        self._pump: Optional[threading.Thread] = None

    # -- caller surface (mirrors ServeReply) ------------------------------
    def done(self) -> bool:
        return self._ev.is_set()

    @property
    def state(self) -> str:
        if self._ev.is_set():
            return "failed" if self._error is not None else "done"
        return f"{self._inner.state}@{self.replica}"

    @property
    def latency_s(self) -> Optional[float]:
        return (None if self.t_reply is None
                else self.t_reply - self.t_submit)

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"fleet decode session not finished (state: "
                f"{self.state})")
        if self._error is not None:
            raise self._error
        return self._value

    def tokens(self, timeout: Optional[float] = None):
        """Iterate the session's generated tokens in order as they
        stream — across migrations and replays, one seamless gapless
        sequence. A failed session raises its error AFTER yielding
        every delivered token; `timeout` bounds each wait for the
        NEXT token."""
        i = 0
        while True:
            with self._stream_cv:
                while (i >= len(self._stream)
                       and not self._stream_closed):
                    if not self._stream_cv.wait(timeout):
                        raise TimeoutError(
                            f"no decode token within {timeout}s "
                            f"(state: {self.state})")
                if i < len(self._stream):
                    tok = self._stream[i]
                else:
                    break
            i += 1
            yield tok
        if self._error is not None:
            raise self._error

    # -- pump internals ----------------------------------------------------
    def _start_pump(self) -> None:
        self._pump = threading.Thread(
            target=self._pump_loop,
            name=f"singa_tpu-fleet-decode-{self.session_id}",
            daemon=True)
        self._pump.start()

    def _ingest(self, i: int, tok: int) -> None:
        """Deliver the current inner reply's i-th token. `i` below the
        delivered count is a resumed ledger re-play: skip it, but
        VERIFY it — prefix divergence is a torn stream."""
        with self._stream_cv:
            if i < len(self._stream):
                if self._stream[i] != tok:
                    raise RuntimeError(
                        f"torn decode stream for session "
                        f"{self.session_id}: resumed replica "
                        f"{self.replica} re-played token {i} as {tok} "
                        f"but {self._stream[i]} was already delivered "
                        "— checkpoint diverged from the delivered "
                        "prefix")
                return
            self._stream.append(int(tok))
            self._stream_cv.notify_all()

    def _finish(self, value, err: Optional[BaseException]) -> None:
        self._value = value
        self._error = err
        self.t_reply = time.perf_counter()
        with self._stream_cv:
            self._stream_closed = True
            self._stream_cv.notify_all()
        self._ev.set()
        if err is None:
            _STATS.decode_replies += 1
        else:
            _STATS.decode_failed += 1

    def _replay_ckpt(self) -> Dict:
        """Build a resume checkpoint from what THIS proxy delivered —
        the only state guaranteed to survive a SIGKILLed replica. KV
        stays None: the target re-prefills prompt + ledger, which is
        bit-identical to the lost slab by construction."""
        with self._stream_cv:
            led = list(self._stream)
        p = self._params
        rem = None
        if p["deadline_abs"] is not None:
            rem = (p["deadline_abs"] - time.perf_counter()) * 1e3
            if rem <= 0:
                raise ServeDeadlineError(
                    f"decode session {self.session_id} deadline "
                    f"passed during re-placement with {len(led)} of "
                    f"{p['n_new']} tokens delivered")
        return {"prompt": p["prompt"],
                "toks": np.asarray(led, np.int32),
                "n_new": p["n_new"],
                "temperature": p["temperature"],
                "top_k": p["top_k"],
                "seed": p["seed"],
                "deadline_ms_left": rem,
                "kv": None}

    def _re_place(self, ckpt: Dict, planned: bool,
                  err: Optional[BaseException] = None) -> None:
        t0 = time.perf_counter()
        with trace_mod.context(self.trace):
            inner, name = self._router._route_decode(
                lambda h: h.resume_decode(ckpt),
                exclude={self.replica}, resume=True)
        if planned:
            self.migrations += 1
            _STATS.decode_migrations += 1
        else:
            _STATS.decode_replays += 1
        self._tried.add(name)
        self.replica = name
        self._inner = inner
        self._router._set_affinity(self.session_id, name)
        trace_mod.record_span(
            "decode_migrate" if planned else "decode_replay",
            t0, time.perf_counter(), trace=self.trace, to=name,
            session=self.session_id,
            delivered=int(np.asarray(ckpt["toks"]).size),
            error=None if err is None else repr(err))

    def _pump_loop(self) -> None:
        from .resilience import annotate_exception

        while True:
            inner = self._inner
            i = 0
            try:
                for tok in inner.tokens():
                    self._ingest(i, int(tok))
                    i += 1
                self._finish(inner.result(0.0), None)
                return
            except ServeMigratedError as e:
                ckpt = e.ckpt
                cap = (self._router.max_failover_hops
                       + len(self._router._slots))
                try:
                    if self.migrations >= cap:
                        raise FleetUnavailableError(
                            f"decode session {self.session_id} "
                            f"migrated {self.migrations} times "
                            f"(bound {cap}) — the fleet is draining "
                            "faster than it serves")
                    if ckpt is None:  # defensive: exporter always
                        ckpt = self._replay_ckpt()  # attaches one
                    self._re_place(ckpt, planned=True, err=e)
                except BaseException as e2:
                    self._finish(None, e2)
                    return
            except (ServePoisonedError, ServeDeadlineError) as e:
                # terminal by contract: a poison verdict poisons every
                # replica in turn, and an expired deadline has expired
                self._finish(None, e)
                return
            except (ServeDispatchError, ServeClosedError) as e:
                if self.hops >= self._router.max_failover_hops:
                    annotate_exception(
                        e, f"fleet decode: {self.hops} replay hop(s) "
                           f"exhausted (max_failover_hops "
                           f"{self._router.max_failover_hops})")
                    self._finish(None, e)
                    return
                self.hops += 1
                try:
                    self._re_place(self._replay_ckpt(), planned=False,
                                   err=e)
                except BaseException as e2:
                    self._finish(None, e2)
                    return
            except BaseException as e:
                self._finish(None, e)
                return


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------
class FleetRouter:
    """Health-aware router + supervisor over N replicas. `replicas`
    are `Replica`-protocol handles (`EngineReplica`, or anything
    duck-typing it); the router starts them, routes `submit()` to the
    least-loaded ready one, fails requests over on replica failure,
    honors shed hints, drains on request, and restarts dead replicas
    (bounded). See the module docstring for the full contract.

    One router per fleet; `submit()` is safe from any number of
    caller threads. The supervisor is one daemon thread; failover
    work runs in the waiting caller's thread (`FleetReply.result`)."""

    def __init__(self, replicas: Sequence, *,
                 max_failover_hops: Optional[int] = None,
                 max_shed_retries: Optional[int] = None,
                 max_shed_sleep_s: Optional[float] = None,
                 health_max_age_s: Optional[float] = None,
                 probe_backoff_ms: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 supervise_interval_s: Optional[float] = None,
                 metrics_every: Optional[int] = None,
                 metrics: Optional["trace_mod.MetricsLogger"] = None,
                 fault_injector=None,
                 seed: Optional[int] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        cfg = get_config()

        def knob(v, key, cast):
            return cast(v if v is not None else cfg[key])

        self.max_failover_hops = knob(max_failover_hops,
                                      "max_failover_hops", int)
        self.max_shed_retries = knob(max_shed_retries,
                                     "max_shed_retries", int)
        self.max_shed_sleep_s = knob(max_shed_sleep_s,
                                     "max_shed_sleep_s", float)
        self.health_max_age_s = knob(health_max_age_s,
                                     "health_max_age_s", float)
        self.probe_backoff_s = knob(probe_backoff_ms,
                                    "probe_backoff_ms", float) / 1e3
        self.max_restarts = knob(max_restarts, "max_restarts", int)
        self.supervise_interval_s = knob(supervise_interval_s,
                                         "supervise_interval_s", float)
        self.metrics_every = knob(metrics_every, "metrics_every", int)
        self.metrics = metrics
        self.fault_injector = fault_injector
        if seed is not None:
            self._seed = int(seed)
        elif fault_injector is not None:
            self._seed = int(getattr(fault_injector, "seed", 0))
        else:
            import os

            self._seed = (os.getpid() << 16) ^ (id(self) & 0xFFFF)
        self._slots: Dict[str, _ReplicaSlot] = {}
        for h in replicas:
            if h.name in self._slots:
                raise ValueError(f"duplicate replica name {h.name!r}")
            self._slots[h.name] = _ReplicaSlot(h)
        self._lock = threading.Lock()
        # Serializes state transitions: a caller thread's _refresh
        # (inside _pick) races the supervisor's sweep — both seeing
        # ready->ejected would double-count the ejection.
        self._tlock = threading.Lock()
        self._running = False
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._submit_idx = 0
        self._event_idx = 0
        # session_id -> replica name: the decode tier's sticky map
        # (ISSUE 17). Guarded by _lock; bounded FIFO so a long-lived
        # router can't grow it without bound.
        self._affinity: Dict[str, str] = {}
        # (time, event, replica, reason) — the fleet transition log
        self.events: List = []
        _STATS._routers.add(self)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._running:
            return self
        seen: Dict = {}
        for slot in self._slots.values():
            slot.handle.start()
            slot.state = "ready"
            tok = getattr(slot.handle, "device_token", lambda: None)()
            if tok is not None and tok in seen:
                import sys

                print(f"singa_tpu: fleet replicas {seen[tok]!r} and "
                      f"{slot.name!r} share one device object; the "
                      "per-device RNG key is single-writer state and "
                      "concurrent dispatcher threads will race it — "
                      "build each replica's model on its own "
                      "device.create_tpu_device()", file=sys.stderr)
            elif tok is not None:
                seen[tok] = slot.name
        self._running = True
        self._stop_ev.clear()
        self._thread = threading.Thread(target=self._supervise,
                                        name="singa_tpu-fleet",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        if not self._running:
            return
        self._running = False
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)
        # decode occupancy snapshot BEFORE the replicas stop (a
        # stopped replica's health has no decode block) — the final
        # record ships it so `aggregate_fleet`/`tools/fleet_top.py`
        # can render per-replica session occupancy post-mortem
        rd = {name: snap["decode"]
              for name, snap in self.replica_snapshot().items()
              if "decode" in snap}
        for slot in self._slots.values():
            if slot.state in ("dead", "failed"):
                continue
            try:
                slot.handle.stop(drain=drain)
            except Exception:
                pass
            slot.state = "stopped"
        # final control-plane record: the TERMINAL counters (replies/
        # failed resolve after routing, so the periodic route records
        # undercount them) — what aggregate_fleet's availability reads
        self._log_metrics("stop", **({"replica_decode": rd} if rd
                                     else {}))

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def warmup(self, *arrays) -> int:
        """Warm every replica's bucket programs (each engine's
        `warmup`); with a prewarmed shared store this is N×
        deserialize, zero traces. Returns total programs warmed."""
        return sum(s.handle.warmup(*arrays)
                   for s in self._slots.values()
                   if s.in_rotation())

    def warm_decode(self, prompt_lens=(), max_new_tokens=None,
                    samplers=()) -> int:
        """Warm every replica's decode-tier executables (fused step,
        scan rungs, cohort prefills, and the `samplers`
        (temperature, top_k) pairs sampled traffic will use) — with
        the shared store armed, N× deserialize-only. Returns total
        executables warmed."""
        return sum(s.handle.warm_decode(prompt_lens, max_new_tokens,
                                        samplers=samplers)
                   for s in self._slots.values()
                   if s.in_rotation()
                   and hasattr(s.handle, "warm_decode"))

    # -- admission --------------------------------------------------------
    def submit(self, *arrays,
               deadline_ms: Optional[float] = None) -> FleetReply:
        """Route one request; returns a `FleetReply`. Raises (counted
        `rejected`) when nothing in rotation can accept it — a loud
        router-terminal refusal, mirroring the engine's submit-time
        errors. `ServeOverloadError` (every replica still shedding
        after the retry budget) and `BucketOverflowError` (no replica
        could ever serve it) propagate with their original types;
        queue-full-everywhere and empty-rotation surface as
        `FleetUnavailableError` — the per-replica `ServeQueueFullError`
        names one replica's queue, which is not what the caller of a
        fleet exhausted on every replica needs to hear."""
        if not self._running:
            raise ServeClosedError("fleet router not running: call "
                                   "start()")
        _STATS.requests += 1
        with self._lock:
            self._submit_idx += 1
            idx = self._submit_idx
        deadline_abs = (None if deadline_ms is None
                        else time.perf_counter() + float(deadline_ms)
                        / 1e3)
        # Trace context (ISSUE 15): born HERE, one per fleet request —
        # unless the caller (submit_with_backoff's retry loop) already
        # opened one, in which case the retried attempts share it.
        # Strictly None while tracing is disabled: no id is generated,
        # no span opens, no wire bytes are added downstream.
        ctx = trace_mod.current_trace()
        tid = (ctx["trace_id"] if ctx
               else (trace_mod.new_trace_id() if trace_mod.enabled()
                     else None))
        try:
            with trace_mod.context(tid):
                with trace_mod.span("submit", request=idx):
                    inner, name = self._route_submit(
                        arrays, deadline_ms, exclude=set(),
                        failover=False)
        except BaseException:
            _STATS.rejected += 1
            # ISSUE 20: a router refusal is a bad availability event
            # too — the error budget doesn't care which side said no
            slo_mod.observe_outcome(False)
            raise
        self._chaos_route(idx, self._slots[name])
        if (self.metrics_every
                and idx % self.metrics_every == 0):
            self._log_metrics("route", replica=name)
        return FleetReply(self, arrays, deadline_abs, inner, name,
                          trace=tid)

    def infer(self, *arrays, timeout: Optional[float] = None,
              deadline_ms: Optional[float] = None):
        return self.submit(*arrays,
                           deadline_ms=deadline_ms).result(timeout)

    def submit_decode(self, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      seed: int = 0,
                      deadline_ms: Optional[float] = None,
                      session_id: Optional[str] = None
                      ) -> FleetDecodeReply:
        """Route one generative session (ISSUE 17) and return its
        `FleetDecodeReply` — a stream + future that survives replica
        drains (live KV-slab migration) and SIGKILLs (ledger replay)
        without tearing or duplicating a single token.

        Placement is session-affine on top of occupancy-aware
        least-depth: a `session_id` that routed before goes back to
        the SAME replica while it has a free KV slot (its warm state
        — radix-shared prefixes, resident slabs — lives there);
        otherwise the fresh `ready` replica with the MOST free KV
        slots wins, ties broken by queue depth. Admission-aware
        re-placement: a replica that sheds (`ServeOverloadError`,
        slot pool exhausted) causes the router to try the OTHER
        replicas first — the hint's `retry_after_ms` is honored, with
        seed-keyed jitter, only when the WHOLE rotation is full, up
        to `max_shed_retries` rounds before the overload propagates
        to the caller (counted `decode_rejected`)."""
        if not self._running:
            raise ServeClosedError("fleet router not running: call "
                                   "start()")
        _STATS.decode_requests += 1
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None, :]
        with self._lock:
            self._submit_idx += 1
            idx = self._submit_idx
        deadline_abs = (None if deadline_ms is None
                        else time.perf_counter()
                        + float(deadline_ms) / 1e3)
        sid = (str(session_id) if session_id is not None
               else f"s{idx}")
        ctx = trace_mod.current_trace()
        tid = (ctx["trace_id"] if ctx
               else (trace_mod.new_trace_id() if trace_mod.enabled()
                     else None))
        try:
            with trace_mod.context(tid):
                with trace_mod.span("submit_decode", request=idx,
                                    session=sid):
                    inner, name = self._route_decode(
                        lambda h: h.submit_decode(
                            prompt, max_new_tokens,
                            temperature=temperature, top_k=top_k,
                            seed=seed, deadline_ms=deadline_ms),
                        exclude=set(), resume=False, affinity=sid)
        except BaseException:
            _STATS.decode_rejected += 1
            raise
        _STATS.decode_routed += 1
        self._set_affinity(sid, name)
        self._chaos_route(idx, self._slots[name])
        params = {"prompt": prompt, "n_new": int(max_new_tokens),
                  "temperature": float(temperature),
                  "top_k": int(top_k), "seed": int(seed),
                  "deadline_abs": deadline_abs}
        r = FleetDecodeReply(self, sid, inner, name, tid, params)
        r._start_pump()
        return r

    # -- routing core -----------------------------------------------------
    def _refresh(self, slot: _ReplicaSlot) -> None:
        """Recompute a slot's rotation state from a fresh health read.
        Never resurrects out-of-rotation states here — ejected/dead
        replicas come back only through the supervisor's probe/restart
        path, so rejoin/restart events are counted exactly once."""
        if slot.state not in ("ready", "degraded"):
            return
        if getattr(slot.handle, "killed", False):
            self._transition(slot, "dead", "replica killed")
            return
        snap = slot.handle.health()
        ts = snap.get("time")
        age = None if ts is None else time.time() - float(ts)
        if age is None or age > self.health_max_age_s:
            self._transition(
                slot, "ejected",
                "stale health snapshot"
                + ("" if age is None else f" ({age:.1f}s old)"))
        elif snap.get("state") == "ready":
            if slot.state != "ready":
                self._transition(slot, "ready", "health ready")
        elif snap.get("state") == "degraded":
            if slot.state != "degraded":
                self._transition(slot, "degraded",
                                 "; ".join(snap.get("reasons") or []))
        else:
            self._transition(slot, "ejected",
                             "health unhealthy: "
                             + "; ".join(snap.get("reasons") or []))

    def _transition(self, slot: _ReplicaSlot, state: str,
                    reason: str) -> None:
        with self._tlock:
            prev = slot.state
            if prev == state:
                return
            slot.state = state
            slot.reason = reason
            was_in = prev in ("ready", "degraded")
            now_in = state in ("ready", "degraded")
            if was_in and not now_in and state != "draining":
                _STATS.ejections += 1
                slot.probe_attempt = 0
                slot.next_probe_t = (time.perf_counter()
                                     + self.probe_backoff_s)
            self.events.append((round(time.time(), 3), state,
                                slot.name, reason))
        self._log_metrics("transition", replica=slot.name,
                          to_state=state, reason=reason)

    def _pick(self, exclude) -> Optional[_ReplicaSlot]:
        """Least-depth among fresh `ready` replicas; `degraded` only
        when nothing is ready. None when rotation is empty.

        Every pick re-reads each candidate's health() — the routing
        contract is FRESH reads, so a replica that died microseconds
        ago never gets one more request on the supervisor's 20 ms
        stale view. That costs O(replicas) cheap dict builds per
        submit (file writes happen only on transitions); a fleet big
        enough to feel it should raise `supervise_interval_s`-paced
        caching here rather than routing on stale state by default."""
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            if slot.name not in exclude:
                self._refresh(slot)
        ready = [s for s in slots if s.state == "ready"
                 and s.name not in exclude]
        pool = ready or [s for s in slots if s.state == "degraded"
                         and s.name not in exclude]
        if not pool:
            return None
        return min(pool, key=lambda s: (s.handle.depth(), s.routed,
                                        s.name))

    def _route_submit(self, arrays, deadline_ms, exclude,
                      failover: bool):
        """Pick + submit with shed-aware retry. `exclude` holds
        already-TRIED replicas (failover); replicas that refuse in
        this call are excluded for the current round only. Returns
        (inner ServeReply, replica name); raises the decisive error
        when nothing accepts."""
        from . import resilience

        shed_round = 0
        while True:
            refused_now: set = set()
            shed_hints: Dict[str, float] = {}
            last_shed: Optional[ServeOverloadError] = None
            while True:
                st = self._pick(exclude | refused_now)
                if st is None and exclude:
                    # every UNtried replica refused or left rotation:
                    # a previously-tried one may have restarted — but
                    # never one that just refused this round
                    st = self._pick(refused_now)
                if st is None:
                    break
                try:
                    with trace_mod.span("route", replica=st.name,
                                        failover=failover):
                        r = st.handle.submit(*arrays,
                                             deadline_ms=deadline_ms)
                except ServeOverloadError as e:
                    _STATS.refused += 1
                    st.refusals += 1
                    shed_hints[st.name] = e.retry_after_ms
                    last_shed = e
                    refused_now.add(st.name)
                    continue
                except ServeQueueFullError:
                    _STATS.refused += 1
                    st.refusals += 1
                    refused_now.add(st.name)
                    continue
                except export_cache.BucketOverflowError:
                    # the ladder is fleet-wide (shared policy): no
                    # other replica could serve it either
                    _STATS.refused += 1
                    raise
                except ServeClosedError as e:
                    # replica died between pick and submit; the racy
                    # post-admission refusal was engine-counted
                    # (err.counted) and must stay on the books
                    if getattr(e, "counted", False):
                        _STATS.refused += 1
                    self._refresh(st)
                    if st.state in ("ready", "degraded"):
                        self._transition(st, "dead",
                                         "submit refused: closed")
                    refused_now.add(st.name)
                    continue
                st.routed += 1
                if failover:
                    _STATS.failovers += 1
                else:
                    _STATS.routed += 1
                return r, st.name
            if shed_hints and shed_round < self.max_shed_retries:
                # the WHOLE rotation shed: honor the smallest hint
                # with seed-keyed jitter so fleets of callers
                # decorrelate, then try again
                shed_round += 1
                _STATS.shed_retries += 1
                delay = resilience.backoff_delay_s(
                    shed_round, max(min(shed_hints.values()), 1.0)
                    / 1e3, jitter=0.5, seed=self._seed,
                    salt="fleet-shed")
                time.sleep(min(delay, self.max_shed_sleep_s))
                continue
            if last_shed is not None:
                raise last_shed
            raise FleetUnavailableError(
                "no replica in rotation can accept the request "
                f"(states: { {s.name: s.state for s in self._slots.values()} })")

    # -- decode routing (ISSUE 17) ----------------------------------------
    def _set_affinity(self, sid: str, name: str) -> None:
        with self._lock:
            self._affinity[sid] = name
            while len(self._affinity) > 4096:  # bounded FIFO
                self._affinity.pop(next(iter(self._affinity)))

    def _decode_free_slots(self, slot: _ReplicaSlot) -> int:
        """Free KV slots from the replica's health surface — the
        per-replica occupancy every heartbeat ships (proc transport)
        or the engine computes live (in-process). Unreadable or
        decode-less health reads as ZERO free slots: fail closed,
        the replica still serves via the least-depth tiebreak."""
        try:
            d = (slot.handle.health() or {}).get("decode") or {}
            return int(d.get("free_slots", 0))
        except Exception:
            return 0

    def _pick_decode(self, exclude,
                     affinity: Optional[str] = None
                     ) -> Optional[_ReplicaSlot]:
        """Session-affine placement over fresh health: the sticky
        replica wins while it is in rotation WITH a free KV slot
        (admission-aware: a full sticky replica re-places instead of
        bouncing off its slot pool); otherwise most-free-slots among
        fresh `ready` replicas, ties by least depth. `degraded` only
        when nothing is ready; None when rotation is empty."""
        with self._lock:
            slots = list(self._slots.values())
            sticky = (self._affinity.get(affinity)
                      if affinity is not None else None)
        for slot in slots:
            if slot.name not in exclude:
                self._refresh(slot)
        ready = [s for s in slots if s.state == "ready"
                 and s.name not in exclude]
        pool = ready or [s for s in slots if s.state == "degraded"
                         and s.name not in exclude]
        if not pool:
            return None
        free = {s.name: self._decode_free_slots(s) for s in pool}
        if sticky is not None and free.get(sticky, 0) > 0:
            for s in pool:
                if s.name == sticky:
                    return s
        return min(pool, key=lambda s: (-free[s.name],
                                        s.handle.depth(), s.routed,
                                        s.name))

    def _route_decode(self, call, exclude, resume: bool,
                      affinity: Optional[str] = None):
        """Pick + place one decode session with shed-aware re-try.
        `call(handle)` performs the placement (`submit_decode` or
        `resume_decode`); a shed replica is excluded for the round
        and the OTHERS are tried before the smallest `retry_after_ms`
        hint is honored — the fleet's answer to one full slot pool is
        the rest of the fleet, not a sleep. Returns (inner ServeReply,
        replica name); raises the decisive error when nothing
        admits."""
        from . import resilience

        shed_round = 0
        while True:
            refused_now: set = set()
            shed_hints: Dict[str, float] = {}
            last_shed: Optional[ServeOverloadError] = None
            while True:
                st = self._pick_decode(exclude | refused_now,
                                       affinity)
                if st is None and exclude:
                    # every UNtried replica refused or left rotation:
                    # a previously-tried one may have restarted
                    st = self._pick_decode(refused_now, affinity)
                if st is None:
                    break
                try:
                    with trace_mod.span("route_decode",
                                        replica=st.name,
                                        resume=resume):
                        r = call(st.handle)
                except ServeOverloadError as e:
                    _STATS.decode_refused += 1
                    st.refusals += 1
                    shed_hints[st.name] = e.retry_after_ms
                    last_shed = e
                    refused_now.add(st.name)
                    continue
                except ServeClosedError as e:
                    if getattr(e, "counted", False):
                        _STATS.decode_refused += 1
                    self._refresh(st)
                    if st.state in ("ready", "degraded"):
                        self._transition(st, "dead",
                                         "decode submit refused: "
                                         "closed")
                    refused_now.add(st.name)
                    continue
                st.routed += 1
                return r, st.name
            if shed_hints and shed_round < self.max_shed_retries:
                shed_round += 1
                _STATS.decode_shed_retries += 1
                delay = resilience.backoff_delay_s(
                    shed_round, max(min(shed_hints.values()), 1.0)
                    / 1e3, jitter=0.5, seed=self._seed,
                    salt="fleet-decode-shed")
                time.sleep(min(delay, self.max_shed_sleep_s))
                continue
            if last_shed is not None:
                raise last_shed
            raise FleetUnavailableError(
                "no replica in rotation can admit the decode session "
                f"(states: { {s.name: s.state for s in self._slots.values()} })")

    # -- chaos (fleet-level FaultInjector kinds) --------------------------
    def _chaos_route(self, idx: int, slot: _ReplicaSlot) -> None:
        inj = self.fault_injector
        if inj is None:
            return
        if inj.should("stale_health", idx):
            slot.handle.freeze_health(self.health_max_age_s * 4.0)
            _STATS.stale_injected += 1
        if inj.should("replica_hang", idx):
            slot.handle.hang_once(inj.hang_s)
            _STATS.hangs_injected += 1
        if inj.should("replica_kill", idx):
            _STATS.kills_injected += 1
            self.kill(slot.name)
        # Process-transport kinds (ISSUE 13): only meaningful on a
        # handle that exposes the hook — an in-process fleet ignores
        # them rather than mis-simulating.
        if inj.should("proc_hang", idx):
            slot.handle.hang_once(inj.hang_s)
            _STATS.hangs_injected += 1
        if inj.should("pipe_stall", idx):
            fn = getattr(slot.handle, "stall_pipe", None)
            if fn is not None:
                fn(inj.hang_s)
                _STATS.pipe_stalls_injected += 1
        if inj.should("torn_frame", idx):
            fn = getattr(slot.handle, "tear_next_frame", None)
            if fn is not None:
                fn()
                _STATS.torn_frames_injected += 1
        if inj.should("proc_sigkill", idx):
            fn = getattr(slot.handle, "sigkill", None)
            if fn is not None:
                # a REAL os.kill(pid, SIGKILL), and nothing else: the
                # router must DISCOVER the death (reader EOF, child
                # exit code), not be told about it
                fn()
                _STATS.kills_injected += 1
        # Network-fault kinds (ISSUE 18): real bytes mangled by the
        # replica's ChaosProxy. Only a tcp-transport handle with an
        # armed proxy exposes the hook-with-effect; everything else
        # no-ops rather than mis-simulating a network it doesn't have.
        nf = getattr(slot.handle, "net_fault", None)
        if nf is not None:
            for kind in ("net_partition", "net_delay", "net_reorder",
                         "net_dup", "net_drip", "net_half_open"):
                if inj.should(kind, idx):
                    nf(kind)
                    _STATS.net_faults_injected += 1
                    if kind == "net_partition":
                        _STATS.net_partitions_injected += 1

    # -- fleet operations -------------------------------------------------
    def kill(self, name: str) -> None:
        """Hard-kill a replica (chaos, or an operator pulling a bad
        node). Queued futures on it reroute via failover; the
        supervisor restarts it within `max_restarts`."""
        slot = self._slots[name]
        slot.handle.kill()
        if slot.state not in ("dead", "failed"):
            self._transition(slot, "dead", "killed")
        slot.next_probe_t = time.perf_counter()

    def drain(self, name: str) -> None:
        """Rolling-restart primitive: take `name` out of rotation
        (nothing new routes to it), let its in-flight dispatch
        finish, and reroute its queued requests through failover.
        Live decode sessions MIGRATE (ISSUE 17): `drain_stop`
        checkpoints each one (KV slab + token ledger + sampling
        config + deadline remainder), the session's stream proxy
        catches the `ServeMigratedError` and resumes the checkpoint
        on another replica — the caller's `FleetDecodeReply` keeps
        yielding, zero tokens lost. The replica ends `stopped` —
        restart it explicitly with `rejoin(name)` when it should
        serve again."""
        slot = self._slots[name]
        self._transition(slot, "draining", "drain requested")
        _STATS.drains += 1
        slot.handle.drain_stop()
        self._transition(slot, "stopped", "drained")

    def rejoin(self, name: str) -> None:
        """Bring a stopped/drained/failed replica back: restart its
        engine and put it in rotation (counted `rejoins`)."""
        slot = self._slots[name]
        slot.handle.restart()
        slot.restarts += 1
        slot.probe_attempt = 0
        _STATS.rejoins += 1
        self._transition(slot, "ready", "manual rejoin")

    # -- supervisor -------------------------------------------------------
    def _supervise(self) -> None:
        while self._running:
            now = time.perf_counter()
            for slot in list(self._slots.values()):
                try:
                    if slot.state == "dead":
                        self._supervise_dead(slot, now)
                    elif slot.state == "ejected":
                        self._supervise_ejected(slot, now)
                    elif slot.state in ("ready", "degraded"):
                        self._refresh(slot)
                except Exception as e:  # a replica bug must not kill
                    # the supervisor: log the event and keep sweeping
                    self.events.append((round(time.time(), 3),
                                        "supervisor_error", slot.name,
                                        repr(e)))
            try:
                self._slo_tick()
            except Exception as e:  # same contract as above
                self.events.append((round(time.time(), 3),
                                    "supervisor_error", "slo",
                                    repr(e)))
            self._stop_ev.wait(self.supervise_interval_s)

    def _slo_tick(self) -> None:
        """ISSUE 20: per-sweep anomaly feed + burn-rate evaluation.
        Strict no-op while the SLO engine is disarmed.  Every signal
        here already exists — slot counters the router keeps, the
        proc transport's heartbeat age and clock estimate — the tick
        only hands them to the detectors."""
        if not slo_mod.enabled():
            return
        for slot in list(self._slots.values()):
            probe_fn = getattr(slot.handle, "slo_probe", None)
            probe = probe_fn() if probe_fn is not None else {}
            slo_mod.note_replica(
                slot.name,
                hb_gap_s=probe.get("hb_gap_s"),
                clock_offset_us=probe.get("clock_offset_us"),
                clock_uncertainty_us=probe.get("clock_uncertainty_us"),
                counters={"refusals": slot.refusals,
                          "failures": slot.failures,
                          "restarts": slot.restarts})
        slo_mod.note_replica(
            "router", counters={"failovers": _STATS.failovers,
                                "rejected": _STATS.rejected,
                                "shed_retries": _STATS.shed_retries})
        slo_mod.tick()

    def _supervise_dead(self, slot: _ReplicaSlot, now: float) -> None:
        if slot.restarts >= self.max_restarts:
            self._transition(
                slot, "failed",
                f"restart budget exhausted ({self.max_restarts})")
            return
        if now < slot.next_probe_t:
            return
        from . import resilience

        try:
            slot.handle.restart()
        except Exception as e:
            slot.probe_attempt += 1
            slot.next_probe_t = now + resilience.backoff_delay_s(
                slot.probe_attempt, self.probe_backoff_s, jitter=0.5,
                seed=self._seed, salt=f"restart/{slot.name}")
            self.events.append((round(time.time(), 3),
                                "restart_failed", slot.name, repr(e)))
            return
        slot.restarts += 1
        slot.probe_attempt = 0
        _STATS.restarts += 1
        self._transition(slot, "ready",
                         f"restarted ({slot.restarts}/"
                         f"{self.max_restarts})")

    def _supervise_ejected(self, slot: _ReplicaSlot,
                           now: float) -> None:
        if now < slot.next_probe_t:
            return
        from . import resilience

        slot.probe_attempt += 1
        _STATS.probes += 1
        if getattr(slot.handle, "killed", False):
            self._transition(slot, "dead", "probe found it dead")
            slot.next_probe_t = now
            return
        snap = slot.handle.health()
        ts = snap.get("time")
        fresh = (ts is not None
                 and time.time() - float(ts) <= self.health_max_age_s)
        if fresh and snap.get("state") in ("ready", "degraded"):
            slot.probe_attempt = 0
            _STATS.rejoins += 1
            self._transition(slot, snap["state"], "rejoined: health "
                             + snap["state"])
            return
        slot.next_probe_t = now + resilience.backoff_delay_s(
            slot.probe_attempt, self.probe_backoff_s, jitter=0.5,
            seed=self._seed, salt=f"probe/{slot.name}")

    # -- observability ----------------------------------------------------
    def export_trace(self, path: str) -> str:
        """Write ONE merged Chrome/Perfetto timeline for the whole
        fleet (ISSUE 15): the router's own span ring plus every
        replica's shipped worker spans, each worker source shifted by
        its estimated monotonic-clock offset (`trace_source`, proc
        transport) so a single `trace_id`'s submit/route/ipc/dispatch
        /reply spans nest correctly ACROSS pids. In-process replicas
        need no source of their own — their spans already live in
        this process's ring."""
        import os as _os

        sources = [{"records": trace_mod.records(),
                    "pid": _os.getpid()}]
        for slot in self._slots.values():
            fn = getattr(slot.handle, "trace_source", None)
            if fn is not None:
                sources.extend(fn() or [])
        return trace_mod.merge_chrome_traces(path, sources)

    def slo_report(self) -> Optional[Dict]:
        """Fleet-merged SLO report (ISSUE 20): the router's own
        sketches exactly merged with every worker's heartbeat-shipped
        cumulative sketches, plus burn rates and live alert states.
        None while the SLO engine is disarmed."""
        return slo_mod.report()

    def replica_snapshot(self) -> Dict[str, Dict]:
        out = {}
        for slot in self._slots.values():
            out[slot.name] = {
                "state": slot.state,
                "reason": slot.reason,
                "depth": slot.handle.depth(),
                "routed": slot.routed,
                "refusals": slot.refusals,
                "restarts": slot.restarts,
            }
            snap_fn = getattr(slot.handle, "transport_snapshot", None)
            if snap_fn is not None:
                t = snap_fn()
                out[slot.name]["transport"] = {
                    k: t[k] for k in
                    ("sent", "delivered", "err_replies",
                     "transport_failed", "ipc_timeouts",
                     "torn_frames_detected", "pending", "heartbeats")}
            # decode-tier occupancy (ISSUE 17): sessions in flight,
            # free KV slots, tokens/sec EMA — from the same health
            # surface routing reads (heartbeat-shipped over proc
            # transport), absent when the replica serves no decode
            try:
                d = (slot.handle.health() or {}).get("decode")
            except Exception:
                d = None
            if d:
                out[slot.name]["decode"] = {
                    "active_sessions": int(d.get(
                        "active_sessions", 0)),
                    "free_slots": int(d.get("free_slots", 0)),
                    "tokens_per_s": float(d.get("tokens_per_s",
                                                0.0))}
                # quant mode (ISSUE 19) rides the same surface; key
                # present only when armed so pre-19 snapshots (and
                # fp32 fleets) serialize byte-identically
                if d.get("quant") and d["quant"] != "off":
                    out[slot.name]["decode"]["quant"] = str(d["quant"])
        return out

    def _log_metrics(self, event: str, **extra) -> None:
        m = self.metrics
        if m is None:
            return
        if event == "route" and "replica_decode" not in extra:
            # periodic route records carry the LIVE per-replica decode
            # occupancy (mid-run, not just the stop-time snapshot)
            rd = {name: snap["decode"]
                  for name, snap in self.replica_snapshot().items()
                  if "decode" in snap}
            if rd:
                extra = dict(extra, replica_decode=rd)
        try:
            with self._lock:
                self._event_idx += 1
                idx = self._event_idx
            states = {}
            for slot in self._slots.values():
                states[slot.state] = states.get(slot.state, 0) + 1
            m.log_step(
                idx, event=event, states=states,
                fleet_requests=_STATS.requests,
                fleet_replies=_STATS.replies,
                fleet_failed=_STATS.failed,
                routed=_STATS.routed, failovers=_STATS.failovers,
                refused=_STATS.refused, rejected=_STATS.rejected,
                decode_requests=_STATS.decode_requests,
                decode_replies=_STATS.decode_replies,
                decode_failed=_STATS.decode_failed,
                decode_migrations=_STATS.decode_migrations,
                decode_replays=_STATS.decode_replays,
                ejections=_STATS.ejections, rejoins=_STATS.rejoins,
                restarts=_STATS.restarts,
                kills_injected=_STATS.kills_injected,
                pipe_stalls_injected=_STATS.pipe_stalls_injected,
                torn_frames_injected=_STATS.torn_frames_injected,
                net_faults_injected=_STATS.net_faults_injected,
                net_partitions_injected=_STATS.net_partitions_injected,
                **extra)
        except Exception:
            pass  # a closed metrics stream must not break routing
