"""ICI/DCN collective communicator over a JAX device mesh.

Reference parity: `src/io/communicator.cc` —
  - `Communicator(nDev)` / `Communicator(local_rank, world_size,
    NcclIdHolder&, buffSize)` → here one class holding a
    `jax.sharding.Mesh` with a `dp` axis; ranks are mesh coordinates.
  - `synch` (ncclAllReduce) → `lax.psum` over the `dp` axis.
  - `fusedSynch` (copy into fusion buffer → one allreduce → scatter
    back) → concat-flat → one psum → split, compiled as one XLA
    program (XLA fuses the copies; the buffer is virtual).
  - `synchHalf/fusedSynchHalf` (fp32→fp16 cast kernels around the
    allreduce) → bf16 casts (the TPU-native half type).
  - `sparsification/fusedSparsification` (top-K / threshold encoding +
    allgather) → mask-compress + psum.
  - `wait()` (stream events) → device fence.

Two execution regimes, reflecting how single-controller JAX works:

  * SPMD regime — called inside `shard_map`/`pjit` with the `dp` axis
    bound: collectives emit real AllReduce HLO over ICI. This is the
    multi-chip path (`dryrun_multichip`, pod training, and the
    8-virtual-device CPU tests).
  * Driver regime — called outside any mapped context (eager
    per-gradient training, the reference's own call pattern). Single
    process: every device already sees the global value, so `synch` is
    an identity fence and `grad_scale` is 1.0. Multi-controller
    (jax.process_count() > 1): each process holds its OWN local
    gradient, so `synch` performs a real cross-process AllReduce — a
    pre-compiled psum executable over a one-device-per-process mesh
    (VERDICT r1 Weak #2) — and `grad_scale` is 1/world. All
    controllers must call collectives in the same order, exactly the
    contract of the reference's per-grad ncclAllReduce.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class NcclIdHolder:
    """Bootstrap-token parity shim.

    Reference: `NcclIdHolder` wraps `ncclUniqueId` shared between
    processes. PJRT multi-controller bootstraps via
    `jax.distributed.initialize` (coordinator address + process id),
    so this object only carries those coordinates for API parity.
    """

    def __init__(self, coordinator_address: Optional[str] = None):
        self.coordinator_address = coordinator_address or os.environ.get(
            "SINGA_TPU_COORDINATOR", "127.0.0.1:8476"
        )


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap. Reference: the MPI ctor of `Communicator`
    (MPI_Init → rank exchange → ncclCommInitRank); here PJRT
    distributed init over DCN."""
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def _axis_bound(name: str) -> bool:
    """True when called under shard_map/pmap with `name` in scope."""
    try:
        lax.axis_index(name)
        return True
    except Exception:
        return False


class Communicator:
    """Reference: `singa::Communicator` (src/io/communicator.cc)."""

    def __init__(self, local_rank: int = 0, world_size: Optional[int] = None,
                 nccl_id: Optional[NcclIdHolder] = None,
                 buff_size: int = 4194304, axis: str = "dp",
                 devices: Optional[Sequence] = None):
        from ..device import _accel_devices

        if nccl_id is not None:
            # Reference: the multiprocess ctor uses the shared
            # ncclUniqueId to join the clique. Here the token carries
            # the PJRT coordinator address; process id/count come from
            # the launcher env (hanging on a missing coordinator is
            # worse than running single-host, so require both). NB:
            # jax.distributed.initialize must run before anything that
            # initializes the XLA backend — even jax.process_count()
            # counts — so probe the distributed state directly.
            n = os.environ.get("SINGA_TPU_NUM_PROCS")
            pid = os.environ.get("SINGA_TPU_PROC_ID")
            if n is not None and pid is not None:
                try:
                    from jax._src.distributed import global_state
                    already = global_state.client is not None
                except Exception:
                    already = False
                if not already:
                    init_distributed(nccl_id.coordinator_address,
                                     num_processes=int(n),
                                     process_id=int(pid))

        devs = list(devices) if devices is not None else _accel_devices()
        if world_size is None:
            world_size = len(devs)
        if len(devs) < world_size:
            raise ValueError(
                f"world_size={world_size} but only {len(devs)} devices"
            )
        self.world_size = world_size
        self.local_rank = local_rank
        # Rank stride is the per-process device count (reference:
        # MPI rank * nDev + local_rank), not the global world size.
        self.global_rank = (jax.process_index() * jax.local_device_count()
                            + local_rank)
        self.buff_size = buff_size  # parity: fusion bucket budget (bytes)
        self.axis = axis
        self.mesh = Mesh(np.asarray(devs[:world_size]), (axis,))
        self._last = None
        self._driver_execs = {}   # (shape, dtype) -> compiled psum
        self._proc_mesh = None    # one-device-per-process mesh (lazy)

    # -- core collectives --------------------------------------------------
    def synch(self, x):
        """AllReduce(sum). Reference: `Communicator::synch` → ncclAllReduce."""
        if _axis_bound(self.axis):
            return lax.psum(x, self.axis)
        if jax.process_count() > 1:
            return self._driver_reduce(x)
        self._last = x
        return x  # driver regime, single controller: value is global

    # -- driver-regime cross-process reduction -----------------------------
    def _get_proc_mesh(self) -> Mesh:
        if self._proc_mesh is None:
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[p] for p in sorted(by_proc)]
            self._proc_mesh = Mesh(np.asarray(devs), ("procs",))
        return self._proc_mesh

    def _driver_reduce(self, x):
        """Eager cross-process AllReduce: every controller contributes
        its local value; a jitted shard_map psum over a
        one-device-per-process mesh sums them (the multi-controller
        analogue of the reference's per-grad ncclAllReduce). Executables
        are cached per (shape, dtype)."""
        from jax.experimental.shard_map import shard_map

        x = jnp.asarray(x)
        mesh = self._get_proc_mesh()
        key = (tuple(x.shape), str(x.dtype))
        fn = self._driver_execs.get(key)
        if fn is None:
            fn = jax.jit(shard_map(
                lambda g: lax.psum(g[0], "procs"),
                mesh=mesh, in_specs=P("procs"), out_specs=P()))
            self._driver_execs[key] = fn
        local_dev = mesh.local_devices[0]
        shard = jax.device_put(x[None], local_dev)
        garr = jax.make_array_from_single_device_arrays(
            (mesh.size,) + tuple(x.shape),
            NamedSharding(mesh, P("procs")), [shard])
        out = fn(garr)
        red = out.addressable_data(0)
        self._last = red
        return red

    def synch_half(self, x):
        """Reference: `synchHalf` — cast to half around the allreduce.
        bf16 keeps fp32 range (no loss-scale dance needed)."""
        y = self.synch(x.astype(jnp.bfloat16))
        return y.astype(x.dtype)

    def fused_synch(self, xs: List):
        """Reference: `fusedSynch` — one allreduce over a fusion buffer.

        Flatten+concat all grads, one psum, split back. Under jit this
        is exactly the reference's fusion-buffer trick with the copies
        fused away by XLA.
        """
        if not xs:
            return xs
        if not _axis_bound(self.axis) and jax.process_count() == 1:
            # Single controller: synch is an identity — skip the
            # flatten/concat/split round-trip entirely. (Multi-
            # controller falls through: synch() below dispatches the
            # flat buffer to the cross-process reduction.)
            self._last = xs[-1]
            return xs
        shapes = [x.shape for x in xs]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        flat = jnp.concatenate([jnp.ravel(x) for x in xs])
        red = self.synch(flat)
        out = []
        off = 0
        for s, n in zip(shapes, sizes):
            out.append(jnp.reshape(red[off:off + n], s))
            off += n
        return out

    def fused_synch_half(self, xs: List):
        """Reference: `fusedSynchHalf` — bf16-compressed fused allreduce."""
        if not xs:
            return xs
        dtypes = [x.dtype for x in xs]
        red = self.fused_synch([x.astype(jnp.bfloat16) for x in xs])
        return [r.astype(d) for r, d in zip(red, dtypes)]

    def sparsification(self, x, spars: float = 0.05, topK: bool = False):
        """Reference: `sparsification` — exchange only significant
        entries. topK: keep the `spars` fraction largest-|g|; else
        threshold at `spars`. Zeroed-out entries contribute nothing to
        the reduction (the reference encodes index/value pairs; dense
        masking is the XLA-friendly equivalent — same math, and the
        mask multiply fuses into the reduce program)."""
        from ..ops import pallas_kernels as _pk

        flat = jnp.ravel(x)
        if topK:
            if _pk.sparsify_enabled():
                # Pallas tier: histogram-threshold kernel (keeps >= K;
                # see pallas_kernels.topk_sparsify).
                masked = _pk.topk_sparsify(flat, spars)
            else:
                k = max(1, int(flat.size * spars))
                thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
                masked = jnp.where(jnp.abs(flat) >= thresh, flat, 0.0)
        elif _pk.sparsify_enabled():
            masked = _pk.threshold_mask(flat, spars)
        else:
            masked = jnp.where(jnp.abs(flat) >= spars, flat, 0.0)
        return jnp.reshape(self.synch(masked), x.shape)

    # -- misc --------------------------------------------------------------
    def wait(self):
        """Reference: `Communicator::wait` — block until comm stream
        drains. Driver regime: fence the last touched array."""
        if self._last is not None:
            try:
                self._last.block_until_ready()
            except AttributeError:
                pass  # tracer (inside jit): ordering handled by XLA
            self._last = None

    @property
    def grad_scale(self) -> float:
        """Multiply grads by this after synch. SPMD regime: 1/world
        (reference semantics: ranks hold per-shard grads). Driver
        regime: 1/nprocs under multi-controller (synch summed one grad
        per process); 1 single-controller (grad already global)."""
        if _axis_bound(self.axis):
            return 1.0 / self.world_size
        n = jax.process_count()
        return 1.0 / n if n > 1 else 1.0

    # -- sharding helpers (TPU-native extras) ------------------------------
    def shard_batch(self, array):
        """Place a global batch array sharded over the dp axis."""
        return jax.device_put(
            array, NamedSharding(self.mesh, P(self.axis))
        )

    def replicate(self, array):
        return jax.device_put(array, NamedSharding(self.mesh, P()))

    def __repr__(self):
        return (f"<Communicator world={self.world_size} axis={self.axis!r} "
                f"mesh={self.mesh.shape}>")
