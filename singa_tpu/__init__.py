"""singa_tpu — a TPU-native deep-learning framework with the
capabilities of Apache SINGA (reference: mlinking/singa).

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

    examples/            train scripts (MLP/CNN/RNN/ONNX)
    sonnx                ONNX import/export over the op registry
    model / layer / opt  training API (Model.compile, Layer, SGD..DistOpt)
    autograd             Operator registry + tape-free backward()
    tensor / device      Tensor over jax.Array; TpuDevice over PJRT
    ops/                 op catalogue as XLA HLO + Pallas kernels
    parallel/            mesh, DP/TP/SP/PP/EP shardings, ring attention
    models/              native flagship models (TransformerLM + decode)
    checkpoint           async checkpoint writer + keep-N rotation
    resilience           step guard, dynamic loss scaling, fault
                         injection, crash-consistent auto-resume
    serve                continuous-batching inference serving tier
                         (admission queue, bucket-padded fused
                         dispatch, SLO percentiles, prewarm)
    fleet                health-aware router over N serving replicas
                         (failover, shed-aware retry, drain,
                         supervised restarts, replica-kill chaos)
    fleet_proc           multi-process replica transport: worker
                         subprocesses behind the same Replica
                         protocol (framed checksummed IPC, heartbeat
                         liveness, IPC deadlines, SIGKILL respawn,
                         exact cross-process reconciliation);
                         fleet_worker is the spawned entrypoint
    converter            Caffe prototxt importer
    io/ + native/        record IO, snapshot, C++ runtime pieces
"""

__version__ = "0.1.0"

from . import autograd  # noqa: F401
from . import checkpoint  # noqa: F401
from . import data  # noqa: F401
from . import device  # noqa: F401
from . import export_cache  # noqa: F401
from . import fleet  # noqa: F401
from . import fleet_proc  # noqa: F401
from . import initializer  # noqa: F401
from . import io  # noqa: F401
from . import layer  # noqa: F401
from . import loss  # noqa: F401
from . import metric  # noqa: F401
from . import model  # noqa: F401
from . import opt  # noqa: F401
from . import resilience  # noqa: F401
from . import rnn  # noqa: F401
from . import serve  # noqa: F401
from . import snapshot  # noqa: F401
from . import sonnx  # noqa: F401
from . import stats  # noqa: F401
from . import tensor  # noqa: F401
from . import trace  # noqa: F401
from .model import Model  # noqa: F401
from .stats import cache_stats, reset_cache_stats  # noqa: F401
from .device import (  # noqa: F401
    CppCPU,
    Device,
    Platform,
    TpuDevice,
    create_cpu_device,
    create_tpu_device,
    create_tpu_device_on,
    get_default_device,
)
from .tensor import Tensor  # noqa: F401
