"""Cost-model-guided autotuner over the step knob space (ISSUE 9;
ROADMAP items 2 + 5).

The knob space — slot dtype x BN-stats dtype x XLA profile x accum
geometry x scan-level remat policy x Pallas block shapes — outgrew
hand-queued bench matrix rows. TVM (arXiv:1802.04799) shows a
cost-model-guided search over exactly this kind of configuration space
beats hand tuning *when candidates can be scored cheaply*; μ-cuDNN
(arXiv:1804.04806) is the precedent for making the memory/recompute
trade (the remat knob) part of that search. Here the cheap scorer is
the CPU-side HLO meter from PR 2:

  * step HBM bytes       — `hlo_profile.bytes_accessed` over the
                           optimized whole-step HLO
                           (`Model.step_hlo_text`),
  * analytic FLOPs       — `hlo_profile.profile_hlo` row sums,
  * peak live bytes      — `hlo_profile.peak_bytes_estimate` over the
                           PRE-optimization HLO (where the remat
                           policy's checkpoint barriers still stand),

combined by a roofline cost model per device kind:

    est. step time = max(bytes / HBM_bandwidth, flops / peak_flops)
    score          = effective_batch / est. step time   (examples/s)

subject to peak_bytes <= the chip's HBM capacity — which is how the
remat knob earns its seat: it never wins the pure roofline (recompute
adds bytes AND flops) but it turns infeasible accum/batch geometries
feasible. The whole search runs on CPU in CI; tunnel windows only
CONFIRM the frontier, never explore it.

Measured scores outrank modeled ones (the TVM lesson): per-config
JSONL from `benchmarks/pallas_tune.py --cpu --jsonl` feeds the Pallas
block-shape axis, and any metrics JSONL whose records carry a
`config` dict (the autotuner's own search log qualifies) overrides
the model for exact config matches.

Search is DETERMINISTIC: proposals come from a seeded
`random.Random`, scoring is pure given the model topology, and the
winner tie-breaks on (score, fewest non-default knobs, canonical
JSON) — the same seed always reproduces the same winner. No
wall-clock enters proposals.

The best-known config persists per (model topology fingerprint, chip
kind) in a JSON store (`TunedStore`) that `bench.py --tuned` and the
serving tier (`serve.ServingEngine`) load by default; the store also
carries name aliases ("resnet") so callers can resolve a config
before the model's params exist.

Counters: `cache_stats()["tuning"]`.
"""
from __future__ import annotations

import hashlib
import json
import os
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import stats as stats_mod

__all__ = [
    "KNOBS",
    "HLO_KNOBS",
    "CHIP_SPECS",
    "normalize_chip",
    "default_config",
    "validate_config",
    "canonical",
    "CostModelScorer",
    "propose",
    "autotune",
    "TunedStore",
    "default_store_path",
    "apply_config",
    "load_best",
    "apply_best_for_serving",
    "ingest_pallas_jsonl",
    "ingest_metrics_jsonl",
    "MeasuredScores",
]


# ---------------------------------------------------------------------------
# Knob space. Values are ORDERED (the proposal enumeration and the
# deterministic tie-break both read this order); the first value of
# every knob is its process default.
# ---------------------------------------------------------------------------
KNOBS: Dict[str, tuple] = {
    # AMP compute dtype (tensor.set_compute_dtype) — the headline
    # bench axis: the byte-diet knobs below only pay off under it
    # (fp32 activations keep fp32 stats and slots convert at fusion
    # boundaries; see tests/test_byte_diet.py)
    "compute_dtype": (None, "bfloat16"),
    # optimizer-slot storage dtype (opt.Optimizer.set_slot_dtype;
    # fp32 master math either way)
    "slot_dtype": (None, "bfloat16", "float16"),
    # BatchNorm statistics precision floor (device.set_bn_stats_dtype)
    "bn_stats_dtype": (None, "bfloat16", "float16"),
    # XLA flag profile (device.set_xla_profile) — cost-model-NEUTRAL
    # (flags change scheduling, not bytes/flops): only a measured
    # score can promote "latency", so the model never hallucinates a
    # win it cannot see.
    "xla_profile": ("default", "latency"),
    # microbatched gradient accumulation (device.set_grad_accum)
    "grad_accum": (1, 2, 4),
    # scan-level rematerialization policy (device.set_remat_policy) —
    # the headline new knob: searchable memory/recompute trade
    "remat_policy": (None, "dots_saveable", "nothing_saveable"),
    # Multi-axis parallel trainer knobs (ISSUE 10; ROADMAP item 3).
    # mesh_geometry: a ParallelPlan axis spec ("data=4,pipe=2") the
    # scorer compiles the step over (None = single device). Values
    # whose axis product does not divide the process's device count
    # score infeasible (loud row reason) rather than erroring — the
    # same knob space serves 1-device CI and the 8-device mesh.
    "mesh_geometry": (None, "data=4,pipe=2", "data=4,model=2",
                      "data=2,model=2,pipe=2", "data=4,expert=2"),
    # pipeline_microbatches: every PipelineStack's microbatch count
    # (None = pipe size); more microbatches shrink the bubble
    # (P-1)/(M+P-1) but shrink per-tick MXU shapes.
    "pipeline_microbatches": (None, 2, 4, 8),
    # moe_capacity_factor: every MoE layer's expert capacity factor
    # (None = the layer/plan setting); higher drops fewer tokens but
    # pads more expert compute.
    "moe_capacity_factor": (None, 1.0, 1.25, 1.5, 2.0),
    # Int8 quantized inference (ISSUE 19; device.set_inference_quant):
    # the byte-diet on the decode/forward path — int8 param payloads
    # + packed KV slab with dequant-at-use. Inference-only (training
    # steps ignore it); the serving score path + measured records are
    # how it earns trust (the TVM lesson), not the analytic model.
    "inference_quant": ("off", "int8"),
    # Pallas kernel block shapes (env-overridable at
    # ops/pallas_kernels import; benchmarks/pallas_tune.py sweeps
    # them). Cost-model-neutral on CPU — they join the search through
    # measured sweep JSONL (`ingest_pallas_jsonl`).
    "pallas_attn_tq": (None, 64, 128, 256, 512),
    "pallas_row_budget": (None, 1 << 17, 1 << 18, 1 << 19, 1 << 20,
                          1 << 21),
    "pallas_hist_budget": (None, 1 << 11, 1 << 12, 1 << 13, 1 << 14,
                           1 << 15),
}

# The subset whose values change the traced/compiled step HLO — the
# score cache keys on exactly these (xla/pallas knobs are neutral to
# the HLO meter, so configs differing only there share a measurement).
HLO_KNOBS = ("compute_dtype", "slot_dtype", "bn_stats_dtype",
             "grad_accum", "remat_policy", "mesh_geometry",
             "pipeline_microbatches", "moe_capacity_factor",
             "inference_quant")

# Pallas knob -> the env var pallas_kernels reads at import, and the
# module global it reads into (apply_config pokes the live module too
# — by apply time ops/pallas_kernels has usually ALREADY been
# imported, so the env var alone would be a silent no-op in-process;
# the kernels re-read the globals at trace time, so later traces pick
# the new blocks up).
PALLAS_ENV = {
    "pallas_attn_tq": "SINGA_TPU_ATTN_TQ",
    "pallas_row_budget": "SINGA_TPU_ROW_BUDGET",
    "pallas_hist_budget": "SINGA_TPU_HIST_BUDGET",
}
PALLAS_ATTR = {
    "pallas_attn_tq": "_ATTN_TQ",
    "pallas_row_budget": "_ROW_BUDGET",
    "pallas_hist_budget": "_HIST_BUDGET",
}


# ---------------------------------------------------------------------------
# Device roofline specs. Bandwidth/peak per chip kind (BASELINE.md pins
# the v5e at ~819 GB/s / 197 bf16 TFLOP/s; the others from published
# TPU system specs). The "cpu" row exists so the search smoke runs
# chip-agnostic in CI — its numbers model a commodity host, and the
# RELATIVE ranking (which is all a search needs) is bandwidth-bound
# like the TPU rows.
# ---------------------------------------------------------------------------
CHIP_SPECS: Dict[str, Dict] = {
    "v5e": {"hbm_gbps": 819.0, "peak_flops": 197e12,
            "hbm_bytes": 16e9},
    "v5p": {"hbm_gbps": 2765.0, "peak_flops": 459e12,
            "hbm_bytes": 95e9},
    "v4": {"hbm_gbps": 1228.0, "peak_flops": 275e12,
           "hbm_bytes": 32e9},
    "v6e": {"hbm_gbps": 1640.0, "peak_flops": 918e12,
            "hbm_bytes": 32e9},
    "cpu": {"hbm_gbps": 50.0, "peak_flops": 1e12,
            "hbm_bytes": 8e9},
}


def normalize_chip(device_kind: str) -> str:
    """Map a PJRT `device_kind` string ("TPU v5 lite", "cpu", ...) to
    a CHIP_SPECS key. Unknown kinds model as the project's target chip
    (v5e) — the search still ranks, the absolute seconds are just
    nominal."""
    name = (device_kind or "").lower()
    if "cpu" in name or "host" in name:
        return "cpu"
    if "v5 lite" in name or "v5e" in name or "v5litepod" in name:
        return "v5e"
    if "v5p" in name or name.endswith("v5") or "v5 " in name:
        return "v5p"
    if "v6" in name:
        return "v6e"
    if "v4" in name:
        return "v4"
    return "v5e"


# ---------------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------------
def default_config(space: Optional[Dict] = None) -> Dict:
    """All-defaults config: the first value of every knob."""
    sp = KNOBS if space is None else space
    return {k: vals[0] for k, vals in sp.items()}


def validate_config(cfg: Dict, space: Optional[Dict] = None) -> Dict:
    """Reject unknown knob NAMES and unknown knob VALUES loudly — a
    typo'd knob silently tuning nothing is exactly the failure mode a
    refusal here prevents. Returns a full config (missing knobs filled
    with their defaults)."""
    sp = KNOBS if space is None else space
    unknown = set(cfg) - set(sp)
    if unknown:
        raise ValueError(
            f"unknown knob name(s) {sorted(unknown)}; known: "
            f"{sorted(sp)}")
    out = default_config(sp)
    for k, v in cfg.items():
        if v not in sp[k]:
            raise ValueError(
                f"unknown value {v!r} for knob {k!r}; known: "
                f"{list(sp[k])}")
        out[k] = v
    return out


def canonical(cfg: Dict) -> str:
    """Stable JSON identity of a config (sorted keys) — the
    deterministic tie-break and the measured-score match key."""
    return json.dumps(cfg, sort_keys=True, default=str)


def _non_default_count(cfg: Dict, space: Optional[Dict] = None) -> int:
    sp = KNOBS if space is None else space
    return sum(1 for k, v in cfg.items()
               if k in sp and v != sp[k][0])


# ---------------------------------------------------------------------------
# Observability: cache_stats()["tuning"]
# ---------------------------------------------------------------------------
class _TuningStats:
    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.proposals = 0
        self.scored = 0
        self.score_cache_hits = 0
        self.measured_hits = 0
        self.infeasible = 0
        self.store_loads = 0
        self.store_saves = 0

    def snapshot(self) -> Dict:
        return {
            "proposals": self.proposals,
            "scored": self.scored,
            "score_cache_hits": self.score_cache_hits,
            "measured_hits": self.measured_hits,
            "infeasible": self.infeasible,
            "store_loads": self.store_loads,
            "store_saves": self.store_saves,
        }


_STATS = _TuningStats()
stats_mod.register_cache("tuning", _STATS)


def tuning_stats() -> _TuningStats:
    return _STATS


# ---------------------------------------------------------------------------
# Measured score sources (the TVM lesson: real numbers outrank the
# model wherever they exist)
# ---------------------------------------------------------------------------
class MeasuredScores:
    """Measured examples/sec per exact config, plus per-knob Pallas
    sweep timings. `lookup(cfg)` returns a measured score only on an
    EXACT canonical match — a near-miss silently standing in for a
    measurement would poison the frontier."""

    def __init__(self):
        self._by_config: Dict[str, float] = {}
        # pallas knob -> {value: best score seen}; normalized
        # (us/us_ref) and raw-microsecond records are kept in
        # SEPARATE pools — ranking a ratio against a raw time would
        # always prefer whichever value happened to carry the
        # reference measurement
        self._pallas_norm: Dict[str, Dict] = {}
        self._pallas_raw: Dict[str, Dict] = {}

    def add_config(self, cfg: Dict, examples_per_sec: float) -> None:
        self._by_config[canonical(cfg)] = float(examples_per_sec)

    def lookup(self, cfg: Dict) -> Optional[float]:
        return self._by_config.get(canonical(cfg))

    def add_pallas(self, knob: str, value, us: float,
                   us_ref: Optional[float] = None) -> None:
        """Record one sweep timing. When the XLA reference time is
        known the stored score is the NORMALIZED ratio us/us_ref —
        one knob can be swept by several cases (ROW_BUDGET rides both
        the xent and dropout sweeps) and by interpret-mode AND
        on-chip runs appended to the same JSONL; raw microseconds
        from different workloads/modes are incomparable, ratios to
        each case's own XLA baseline are scale-free."""
        pool = self._pallas_norm if us_ref else self._pallas_raw
        score = us / us_ref if us_ref else us
        d = pool.setdefault(knob, {})
        if value not in d or score < d[value]:
            d[value] = float(score)

    def best_pallas_value(self, knob: str):
        """argmin value for one pallas knob (None when unswept).
        Normalized records win outright when any exist for the knob —
        they are the workload-comparable pool."""
        d = self._pallas_norm.get(knob) or self._pallas_raw.get(knob)
        if not d:
            return None
        return min(sorted(d, key=lambda v: (v is None, v)),
                   key=lambda v: d[v])

    def pallas_knobs_swept(self) -> List[str]:
        return sorted(set(self._pallas_norm) | set(self._pallas_raw))


def ingest_pallas_jsonl(path: str,
                        into: Optional[MeasuredScores] = None
                        ) -> MeasuredScores:
    """Read the per-config JSONL emitted by
    `benchmarks/pallas_tune.py --jsonl`: records
    {"case", "knob", "value", "us", "us_ref"} keyed by the env-var
    knob name. Partial trailing lines (a killed sweep) are skipped —
    the `trace.read_metrics` contract."""
    ms = into if into is not None else MeasuredScores()
    env_to_knob = {v: k for k, v in PALLAS_ENV.items()}
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return ms
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            r = json.loads(line)
        except ValueError:
            continue  # partial trailing line
        knob = env_to_knob.get(r.get("knob"), r.get("knob"))
        if knob in PALLAS_ENV and "us" in r:
            ref = r.get("us_ref")
            ms.add_pallas(knob, r.get("value"), float(r["us"]),
                          us_ref=float(ref) if ref else None)
    return ms


def ingest_metrics_jsonl(path: str,
                         into: Optional[MeasuredScores] = None,
                         chip: Optional[str] = None,
                         batch: Optional[int] = None
                         ) -> MeasuredScores:
    """Read measured examples/sec from a metrics JSONL whose records
    carry a `config` dict (`bench.py` resnet runs append such records
    to metrics/measured_configs.jsonl). Records without a config are
    skipped — there is nothing exact to match them to. `chip`/`batch`
    filters (pass the chip being tuned and the effective batch being
    scored) drop records measured elsewhere: a CPU toy-geometry run's
    tens of img/s must never override a v5e candidate's modeled
    thousands — the exact frontier-poisoning `MeasuredScores.lookup`'s
    exact-match rule exists to prevent. A filtered field missing from
    a record fails CLOSED (skipped)."""
    ms = into if into is not None else MeasuredScores()
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return ms
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            r = json.loads(line)
        except ValueError:
            continue
        cfg = r.get("config")
        eps = r.get("measured_examples_per_sec",
                    r.get("examples_per_sec"))
        if chip is not None and r.get("chip") != chip:
            continue
        if batch is not None and r.get("batch") != batch:
            continue
        if isinstance(cfg, dict) and eps and r.get(
                "source") == "measured":
            try:
                ms.add_config(validate_config(cfg), float(eps))
            except ValueError:
                continue  # foreign schema: not this knob space
    return ms


# ---------------------------------------------------------------------------
# The scorer
# ---------------------------------------------------------------------------
class CostModelScorer:
    """Scores one config WITHOUT a chip.

    `model_factory()` must return a fresh `(model, optimizer)` pair
    per call (configs mutate optimizer slot policy and process knobs,
    so instances are never reused across configs);
    `make_inputs()` returns the effective-batch input Tensors
    (inputs-then-labels, exactly what `train_one_batch` takes).

    Scoring lowers the whole-step program at the config's MICROBATCH
    geometry (grad_accum=n scans n microbatches whose per-iteration
    cost is what the roofline needs; the analytic step estimate is
    n x the microbatch lowering, which over-counts the once-per-step
    optimizer apply by (n-1) — a conservative bias against
    accumulation, documented here rather than hidden) and reads the
    traffic/FLOP meters there; `peak_bytes` comes from the FULL accum
    geometry's pre-optimization HLO (the real scan program, where a
    remat policy's smaller saveable set shrinks the loop body's max
    live set — pre-opt text pays tracing but no second XLA compile).
    Results are cached per HLO-affecting knob
    subset (HLO_KNOBS): xla/pallas axes are meter-neutral, so configs
    differing only there share one measurement.
    """

    def __init__(self, model_factory: Callable,
                 make_inputs: Callable,
                 chip: str = "v5e",
                 measured: Optional[MeasuredScores] = None):
        if chip not in CHIP_SPECS:
            raise ValueError(
                f"unknown chip {chip!r}; known: {sorted(CHIP_SPECS)}")
        self.model_factory = model_factory
        self.make_inputs = make_inputs
        self.chip = chip
        self.measured = measured
        self._hlo_cache: Dict[tuple, Dict] = {}
        self._fingerprint: Optional[str] = None

    @property
    def fingerprint(self) -> Optional[str]:
        """Topology fingerprint of the scored model (available after
        the first score): the store key."""
        return self._fingerprint

    def _hlo_key(self, cfg: Dict) -> tuple:
        def h(v):
            return tuple(v) if isinstance(v, (list, tuple)) else v

        return tuple((k, h(cfg[k])) for k in HLO_KNOBS)

    def _measure(self, cfg: Dict) -> Dict:
        """Lower the step under this config's HLO-affecting knobs and
        read the meters. Process knobs are snapshotted and restored —
        scoring must never leak a candidate's knobs into the live
        process."""
        from . import hlo_profile

        from . import tensor as tensor_mod

        n = int(cfg["grad_accum"])
        # Multi-axis knobs (ISSUE 10): a mesh geometry compiles the
        # step as the SPMD program over a ParallelPlan mesh and the
        # roofline divides by the device count (SPMD splits bytes and
        # flops; the collectives' traffic is the documented
        # approximation error). Infeasible geometries (axis product
        # not dividing the process's devices) score -inf with a loud
        # reason instead of erroring — the knob space is shared
        # between 1-device CI and the 8-device mesh.
        geom = cfg["mesh_geometry"]
        plan = None
        ndev = 1
        if geom is not None:
            from .parallel import plan as plan_mod

            axes = plan_mod.parse_geometry(geom)
            plan = plan_mod.ParallelPlan(**axes)
            try:
                # the real feasibility oracle: auto_mesh's own rules
                # (explicit axes must use the devices exactly; a
                # divisor-only pre-check would admit e.g. an 8-device
                # geometry on a 16-device backend and then crash the
                # sweep inside compile)
                mesh = plan.build_mesh()
            except ValueError as e:
                _STATS.infeasible += 1
                return {"feasible": False, "score": float("-inf"),
                        "reason": f"mesh {geom}: {e}"}
            ndev = 1
            for v in mesh.shape.values():
                ndev *= int(v)
        saved = stats_mod.get_config()
        saved_cd = tensor_mod.get_compute_dtype()
        try:
            tensor_mod.set_compute_dtype(cfg["compute_dtype"])
            stats_mod.configure(
                bn_stats_dtype=cfg["bn_stats_dtype"],
                remat_policy=cfg["remat_policy"],
                grad_accum=1,
                pipeline_microbatches=cfg["pipeline_microbatches"],
                moe_capacity_factor=cfg["moe_capacity_factor"],
                # donation off for the measurement: the aliasing
                # copies XLA inserts for donated buffers are noise on
                # top of the program's real dataflow (the
                # test_byte_diet metering discipline)
                buffer_donation=False)
            model, optimizer = self.model_factory()
            if cfg["slot_dtype"] is not None:
                optimizer.set_slot_dtype(cfg["slot_dtype"])
            model.set_optimizer(optimizer)
            inputs = self.make_inputs()
            batch = int(inputs[0].shape[0])
            if batch % n:
                _STATS.infeasible += 1
                return {"feasible": False, "score": float("-inf"),
                        "reason": f"batch {batch} not divisible by "
                                  f"grad_accum {n}"}
            plan_kw = {} if plan is None else {"plan": plan}
            mb_inputs = [self._slice_mb(t, batch // n) for t in inputs]
            model.compile([mb_inputs[0]], is_train=True,
                          use_graph=True, grad_accum=1, **plan_kw)
            if self._fingerprint is None:
                self._fingerprint = model.topology_fingerprint()
            opt_text = model.step_hlo_text(*mb_inputs)
            mb_bytes = hlo_profile.bytes_accessed(opt_text)["total"]
            mb_flops = sum(r["flops"]
                           for r in hlo_profile.profile_hlo(opt_text))
            if n > 1:
                # Peak liveness must be metered on the REAL program —
                # the n-microbatch scan, where the estimator recurses
                # into the loop body and a remat policy's smaller
                # saveable set actually shrinks the max live set
                # (tests/test_remat_policy.py pins the strict drop).
                # Pre-optimization text only: no second XLA compile.
                stats_mod.configure(grad_accum=n)
                full_model, full_opt = self.model_factory()
                if cfg["slot_dtype"] is not None:
                    full_opt.set_slot_dtype(cfg["slot_dtype"])
                full_model.set_optimizer(full_opt)
                full_model.compile([inputs[0]], is_train=True,
                                   use_graph=True, grad_accum=n,
                                   **plan_kw)
                pre_text = full_model.step_hlo_text(
                    *inputs, optimized=False)
            else:
                pre_text = model.step_hlo_text(*mb_inputs,
                                               optimized=False)
            peak = hlo_profile.peak_bytes_estimate(pre_text)
        finally:
            tensor_mod.set_compute_dtype(saved_cd)
            stats_mod.configure(
                bn_stats_dtype=saved["bn_stats_dtype"],
                remat_policy=saved["remat_policy"],
                grad_accum=saved["grad_accum"],
                pipeline_microbatches=saved["pipeline_microbatches"],
                moe_capacity_factor=saved["moe_capacity_factor"],
                buffer_donation=saved["buffer_donation"])
        spec = CHIP_SPECS[self.chip]
        step_bytes = n * mb_bytes
        step_flops = n * mb_flops
        # CHIP_SPECS peaks are the MXU's native bf16 numbers; fp32
        # compute runs at roughly half of it — the flops side of the
        # AMP knob (the bytes side is measured directly).
        peak_flops = spec["peak_flops"] * (
            1.0 if cfg["compute_dtype"] == "bfloat16" else 0.5)
        # Mesh geometries meter the GLOBAL SPMD program: per-chip
        # roofline time divides bytes/flops/liveness by the device
        # count (SPMD splits the work; collective traffic rides inside
        # the measured bytes — a conservative over-count per chip).
        est = max(step_bytes / ndev / (spec["hbm_gbps"] * 1e9),
                  step_flops / ndev / peak_flops)
        feasible = peak / ndev <= spec["hbm_bytes"]
        if not feasible:
            _STATS.infeasible += 1
        return {
            "feasible": feasible,
            "score": (batch / est if feasible and est > 0
                      else float("-inf")),
            "est_step_s": est,
            "bytes": step_bytes,
            "flops": step_flops,
            "mb_bytes": mb_bytes,
            "peak_bytes": peak,
            "n_devices": ndev,
            "effective_batch": batch,
            "microbatch": batch // n,
        }

    @staticmethod
    def _slice_mb(t, mb: int):
        from . import tensor as tensor_mod

        if int(t.shape[0]) == mb:
            return t
        return tensor_mod.from_raw(t.data[:mb], t.device)

    def score(self, cfg: Dict) -> Dict:
        """Full score row for one (validated) config: cost-model
        roofline, measured override when an exact match exists, cache
        hit accounting."""
        cfg = validate_config(cfg)
        key = self._hlo_key(cfg)
        cached = key in self._hlo_cache
        if cached:
            _STATS.score_cache_hits += 1
            base = dict(self._hlo_cache[key])
        else:
            base = self._measure(cfg)
            self._hlo_cache[key] = dict(base)
            _STATS.scored += 1
        base["cached"] = cached
        base["source"] = "cost-model"
        base["chip"] = self.chip
        base["config"] = dict(cfg)
        if self.measured is not None:
            m = self.measured.lookup(cfg)
            if m is not None and base.get("feasible", False):
                base["score"] = m
                base["source"] = "measured"
                _STATS.measured_hits += 1
        return base


# ---------------------------------------------------------------------------
# Deterministic search
# ---------------------------------------------------------------------------
def propose(space: Optional[Dict] = None, budget: int = 16,
            seed: int = 0,
            measured: Optional[MeasuredScores] = None) -> List[Dict]:
    """Deterministic candidate list, coordinate-descent flavored:

      1. the default config (the baseline every comparison needs),
      2. every SINGLE-knob flip in knob/value enumeration order —
         the axis sweep that isolates each knob's own effect (and
         costs almost nothing for HLO-neutral axes: the score cache
         collapses them onto the default's measurement),
      3. seeded random fill from the remaining cartesian product when
         budget remains.

    No wall clock, no global RNG — `seed` alone fixes the proposals.
    When `measured` carries Pallas sweep data, candidates' swept
    pallas knobs snap to their measured-best values (that axis was
    already searched for real; the budget goes to the axes only the
    cost model can rank). `autotune` reserves one extra slot for the
    greedy combination of the winning single flips."""
    sp = KNOBS if space is None else space
    if budget < 1:
        raise ValueError("budget must be >= 1")
    keys = list(sp)
    base = default_config(sp)
    picks = [dict(base)]
    for k in keys:
        for v in sp[k][1:]:
            picks.append(dict(base, **{k: v}))
    if len(picks) > budget:
        picks = picks[:budget]
    elif len(picks) < budget:
        # Random fill samples the cartesian product BY INDEX — the
        # full space runs to millions of configs for the real KNOBS
        # ladder, so materializing it (the old implementation) cost
        # ~65 s per call. `random.sample` draws positions, not
        # values, so sampling `range(n_rest)` and mixed-radix
        # decoding each index yields the exact candidate list the
        # materialized version produced for every (space, budget,
        # seed) — determinism contract unchanged.
        sizes = [len(sp[k]) for k in keys]
        strides = [0] * len(keys)
        acc = 1
        for i in range(len(keys) - 1, -1, -1):
            strides[i] = acc
            acc *= sizes[i]
        total = acc
        seen_ix = sorted({
            sum(sp[k].index(c[k]) * strides[i]
                for i, k in enumerate(keys)) for c in picks})
        rng = random.Random(seed)
        need = min(budget - len(picks), total - len(seen_ix))
        for j in rng.sample(range(total - len(seen_ix)), need):
            # shift past the already-picked (single-flip) indices to
            # land on the j-th REMAINING config in product order
            for s in seen_ix:
                if s <= j:
                    j += 1
                else:
                    break
            picks.append({k: sp[k][(j // strides[i]) % sizes[i]]
                          for i, k in enumerate(keys)})
    if measured is not None:
        snapped = []
        seen = set()
        for c in picks:
            c = dict(c)
            for knob in measured.pallas_knobs_swept():
                if knob in c and c[knob] == sp[knob][0]:
                    # only non-swept (default) positions snap: the
                    # axis-sweep candidates for the pallas knob itself
                    # must stay distinct
                    best = measured.best_pallas_value(knob)
                    if best in sp.get(knob, ()):
                        c[knob] = best
            key = canonical(c)
            if key not in seen:
                seen.add(key)
                snapped.append(c)
        picks = snapped
    _STATS.proposals += len(picks)
    return picks


def _greedy_combo(rows: List[Dict], space: Dict) -> Optional[Dict]:
    """Combine, per knob, the best single-flip value that STRICTLY
    beat the baseline row — the coordinate-descent exploitation step.
    None when no flip improved (or the combo isn't novel). The
    baseline is rows[0]'s CONFIG, not `default_config` — with a
    Pallas sweep armed, `propose` snaps every candidate's untouched
    pallas knobs to the measured best (baseline included), so flips
    must be measured against the snapped baseline or no row would
    ever differ by exactly one knob."""
    base = rows[0]["config"]
    base_score = rows[0]["score"]
    combo = dict(base)
    improved = False
    for k in space:
        best_v, best_s = base[k], base_score
        for r in rows:
            cfg = r["config"]
            diffs = [kk for kk in space
                     if cfg.get(kk, space[kk][0]) != base[kk]]
            if diffs == [k] and r.get("feasible") \
                    and r["score"] > best_s:
                best_v, best_s = cfg[k], r["score"]
        if best_v != base[k]:
            combo[k] = best_v
            improved = True
    if not improved:
        return None
    seen = {canonical(r["config"]) for r in rows}
    return combo if canonical(combo) not in seen else None


def autotune(scorer: CostModelScorer, budget: int = 16, seed: int = 0,
             space: Optional[Dict] = None,
             jsonl_path: Optional[str] = None,
             log: Optional[Callable] = None) -> Dict:
    """Run the search: propose -> score -> pick. Appends one JSON line
    per candidate to `jsonl_path` (the stream
    `tools/tpu_watch.sh tune` pretty-tails) and returns
    {"best", "best_score", "default_score", "rows", ...}. Winner
    selection is a pure function of the scored rows: max score, then
    FEWEST non-default knobs (never flip a knob the model can't
    justify), then canonical JSON — so reruns with the same seed
    produce the same winner, always."""
    sp = KNOBS if space is None else space
    # one budget slot is reserved for the greedy combination of the
    # winning single flips (the exploitation step)
    proposals = propose(sp, budget=max(1, budget - 1), seed=seed,
                        measured=scorer.measured)
    rows = []
    sink = None
    if jsonl_path:
        d = os.path.dirname(jsonl_path)
        if d:
            os.makedirs(d, exist_ok=True)
        sink = open(jsonl_path, "a")

    def run_one(i, cfg, tag=""):
        row = scorer.score(cfg)
        row["i"] = i
        row["seed"] = seed
        rows.append(row)
        if sink is not None:
            clean = {k: v for k, v in row.items()
                     if v != float("-inf")}
            sink.write(json.dumps(clean, default=str) + "\n")
            sink.flush()
        if log is not None:
            log(f"[{i + 1}] score={row['score']:.1f} "
                f"{'(cached) ' if row['cached'] else ''}{tag}"
                f"{_fmt_cfg(row['config'], sp)}")
        return row

    try:
        for i, cfg in enumerate(proposals):
            run_one(i, cfg)
        if len(rows) < budget:
            combo = _greedy_combo(rows, sp)
            if combo is not None:
                run_one(len(rows), combo, tag="combo: ")
    finally:
        if sink is not None:
            sink.close()
    feasible = [r for r in rows if r.get("feasible")]
    pool = feasible if feasible else rows

    def rank(r):
        # max score; then fewest non-default knobs (never flip a knob
        # the model can't justify); then EARLIEST proposal — knob/
        # value enumeration order, so ties resolve to the first-listed
        # (preferred) value deterministically
        return (r["score"], -_non_default_count(r["config"], sp),
                -r["i"])

    best = max(pool, key=rank)
    default_row = rows[0]
    return {
        "best": best["config"],
        "best_score": best["score"],
        "best_row": best,
        "default_score": default_row["score"],
        "default_row": default_row,
        "beats_default": best["score"] > default_row["score"],
        "evaluated": len(rows),
        "rows": rows,
        "seed": seed,
        "chip": scorer.chip,
    }


def _fmt_cfg(cfg: Dict, space: Optional[Dict] = None) -> str:
    sp = KNOBS if space is None else space
    nd = {k: v for k, v in cfg.items()
          if k in sp and v != sp[k][0]}
    return "default" if not nd else " ".join(
        f"{k}={v}" for k, v in sorted(nd.items()))


# ---------------------------------------------------------------------------
# Persistent best-known store
# ---------------------------------------------------------------------------
STORE_SCHEMA = 1


def default_store_path() -> str:
    """`SINGA_TPU_TUNED_STORE` env override, else
    `.tuned/tuned_configs.json` under the working directory (bench.py
    pins it next to the repo via the env var)."""
    return os.environ.get("SINGA_TPU_TUNED_STORE") or os.path.join(
        ".tuned", "tuned_configs.json")


class TunedStore:
    """JSON store of best-known configs keyed by
    `(topology fingerprint, chip kind)`, plus a name->fingerprint
    alias map so `bench.py --tuned` can resolve "resnet" before the
    model's params exist. Writes are atomic (tmp + os.replace); a
    corrupt store reads as empty with a loud stderr note — a bad
    cache entry must cost a re-tune, never a crash."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_store_path()

    def _read(self) -> Dict:
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("schema") != STORE_SCHEMA:
                raise ValueError(
                    f"schema {data.get('schema')} != {STORE_SCHEMA}")
            return data
        except FileNotFoundError:
            return {"schema": STORE_SCHEMA, "entries": {},
                    "aliases": {}}
        except (OSError, ValueError) as e:
            import sys

            print(f"singa_tpu: tuned store {self.path!r} unreadable "
                  f"({type(e).__name__}: {e}); treating as empty",
                  file=sys.stderr)
            return {"schema": STORE_SCHEMA, "entries": {},
                    "aliases": {}}

    def put(self, fingerprint: str, chip: str, config: Dict,
            score: float, provenance: Optional[Dict] = None,
            alias=None) -> Dict:
        """`alias` may be one name or a list of them — a model is
        commonly addressed at several granularities ("resnet-18" AND
        "resnet"); all map to this fingerprint, latest put wins."""
        config = validate_config(config)
        data = self._read()
        entry = {
            "config": config,
            "score": float(score),
            "chip": chip,
            "fingerprint": fingerprint,
            "provenance": dict(provenance or {},
                               created=time.time()),
        }
        data["entries"][f"{fingerprint}@{chip}"] = entry
        for a in ([alias] if isinstance(alias, str) else alias or ()):
            data["aliases"][a] = fingerprint
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)
        _STATS.store_saves += 1
        return entry

    def get(self, fingerprint: Optional[str] = None,
            alias: Optional[str] = None,
            chip: Optional[str] = None) -> Optional[Dict]:
        data = self._read()
        fp = fingerprint
        if fp is None and alias is not None:
            fp = data["aliases"].get(alias)
        if fp is None:
            return None
        if chip is not None:
            ent = data["entries"].get(f"{fp}@{chip}")
            if ent is not None:
                _STATS.store_loads += 1
            return ent
        for key in sorted(data["entries"]):
            if key.startswith(f"{fp}@"):
                _STATS.store_loads += 1
                return data["entries"][key]
        return None

    def entries(self) -> Dict:
        return self._read()["entries"]


# ---------------------------------------------------------------------------
# Applying a config to the live process
# ---------------------------------------------------------------------------
def apply_config(cfg: Dict, optimizer=None, apply_xla: bool = False,
                 training: bool = True) -> Dict:
    """Arm the process knobs a config names. `optimizer` receives the
    slot-dtype policy when given. `apply_xla=True` also applies the
    XLA flag profile — only meaningful BEFORE backend init (bench
    stage subprocesses; see device.set_xla_profile). Pallas block
    knobs export their env vars (read at ops/pallas_kernels import —
    arm them before the first singa_tpu.ops import to take effect).
    `training=False` applies only the forward-safe subset (BN stats
    floor + pallas envs): the serving tier must not arm training
    geometry. Returns the applied subset."""
    from . import device

    cfg = validate_config(cfg)
    applied: Dict = {}
    if apply_xla and cfg["xla_profile"] != "default":
        device.set_xla_profile(cfg["xla_profile"])
        applied["xla_profile"] = cfg["xla_profile"]
    if cfg["bn_stats_dtype"] is not None:
        device.set_bn_stats_dtype(cfg["bn_stats_dtype"])
        applied["bn_stats_dtype"] = cfg["bn_stats_dtype"]
    # inference-only knob: forward-safe by construction (training
    # steps never read it), so it applies in BOTH modes
    if cfg["inference_quant"] != "off":
        device.set_inference_quant(cfg["inference_quant"])
        applied["inference_quant"] = cfg["inference_quant"]
    import sys as _sys

    pk = _sys.modules.get("singa_tpu.ops.pallas_kernels")
    for knob, env in PALLAS_ENV.items():
        if cfg[knob] is not None:
            os.environ[env] = str(cfg[knob])
            if pk is not None:
                setattr(pk, PALLAS_ATTR[knob], int(cfg[knob]))
            applied[knob] = cfg[knob]
    if training:
        if cfg["compute_dtype"] is not None:
            from . import tensor as tensor_mod

            tensor_mod.set_compute_dtype(cfg["compute_dtype"])
            applied["compute_dtype"] = cfg["compute_dtype"]
        if cfg["grad_accum"] != 1:
            device.set_grad_accum(cfg["grad_accum"])
            applied["grad_accum"] = cfg["grad_accum"]
        if cfg["remat_policy"] is not None:
            device.set_remat_policy(cfg["remat_policy"])
            applied["remat_policy"] = cfg["remat_policy"]
        if optimizer is not None and cfg["slot_dtype"] is not None:
            optimizer.set_slot_dtype(cfg["slot_dtype"])
            applied["slot_dtype"] = cfg["slot_dtype"]
        # multi-axis trainer knobs (ISSUE 10): training geometry —
        # never armed for serving
        if cfg["mesh_geometry"] is not None:
            from .parallel import plan as plan_mod

            device.set_parallel_plan(
                plan_mod.plan_from_geometry(cfg["mesh_geometry"]))
            applied["mesh_geometry"] = cfg["mesh_geometry"]
        if cfg["pipeline_microbatches"] is not None:
            from . import stats as _stats

            _stats.configure(
                pipeline_microbatches=cfg["pipeline_microbatches"])
            applied["pipeline_microbatches"] = \
                cfg["pipeline_microbatches"]
        if cfg["moe_capacity_factor"] is not None:
            from . import stats as _stats

            _stats.configure(
                moe_capacity_factor=cfg["moe_capacity_factor"])
            applied["moe_capacity_factor"] = cfg["moe_capacity_factor"]
    return applied


def _current_chip() -> str:
    try:
        import jax

        d = jax.devices()[0]
        return normalize_chip(
            f"{d.platform} {getattr(d, 'device_kind', '')}")
    except Exception:
        return "cpu"


def load_best(model=None, alias: Optional[str] = None,
              chip: Optional[str] = None,
              store_path: Optional[str] = None) -> Optional[Dict]:
    """Best-known entry for a model (by live topology fingerprint) or
    an alias, on `chip` (default: the current backend's kind), with
    an any-chip fallback: the autotuner models the TARGET chip (v5e)
    even on a CPU backend, so a strict live-chip lookup would find
    nothing in every CI/off-chip environment. None when the store has
    nothing — callers fall back to defaults. The returned entry names
    its `chip`; consumers log it."""
    store = TunedStore(store_path)
    if not os.path.exists(store.path):
        return None
    fp = model.topology_fingerprint() if model is not None else None
    return store.get(fingerprint=fp, alias=alias,
                     chip=chip or _current_chip()) \
        or store.get(fingerprint=fp, alias=alias)


def apply_best_for_serving(model, store_path: Optional[str] = None
                           ) -> Optional[Dict]:
    """The serving tier's default-load hook (`serve.ServingEngine`):
    look the model up in the tuned store and arm the FORWARD-SAFE
    subset of its best-known config (BN-stats floor, pallas block
    envs — never training geometry). A missing store or entry is a
    silent no-op; a hit is one stderr line so operators can see which
    config is serving."""
    try:
        ent = load_best(model=model, store_path=store_path)
    except Exception:
        return None
    if ent is None:
        return None
    try:
        applied = apply_config(ent["config"], training=False)
    except ValueError:
        return None
    if applied:
        import sys

        print("singa_tpu: serving with tuned config "
              f"{applied} (score {ent.get('score'):.1f}, chip "
              f"{ent.get('chip')})", file=sys.stderr)
    return ent
