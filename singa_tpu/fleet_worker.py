"""Fleet worker entrypoint (ISSUE 13): one serving replica in its own
process. `singa_tpu.fleet_proc.ProcReplica` spawns this module
(`python -m singa_tpu.fleet_worker`) with the replica spec in
`SINGA_TPU_FLEET_SPEC`; the worker

  1. forces the jax platform the parent named (`JAX_PLATFORMS` —
     tier-1 hermeticity: a CPU-pinned test suite must never have a
     worker wander onto an accelerator),
  2. arms the SHARED export-cache store (populate-once-start-N: with
     `tools/prewarm.py` run once, this boot — and every respawn after
     a SIGKILL — is deserialize-only, export hits >= 1, traces == 0),
  3. builds the model from the spec's deterministic factory
     ("module:callable", the `tools/prewarm.py --factory` idiom) and
     runs a `ServingEngine` over it,
  4. serves the framed request/reply protocol of
     `singa_tpu.fleet_proc` over a loopback socket: REQ -> sync ACK
     (admission verdicts keep their exact single-engine error types)
     -> REP/ERR per request; HB heartbeats carry the engine `health()`
     snapshot plus the terminal/export counters the parent's
     reconciliation and deserialize-only pins read; a DRAIN control
     ships the final counters (BYE) — the end-of-run reconciliation
     handshake — before a clean exit 0.

The worker exits when the parent does (socket EOF): no orphans. It
never writes to stdout (the parent may be a bench stage whose stdout
is a JSON contract); logs go to stderr."""
from __future__ import annotations

import json
import os
import struct
import sys
import threading
import time

import numpy as np


def _log(msg: str) -> None:
    print(f"[fleet-worker {os.getpid()}] {msg}", file=sys.stderr,
          flush=True)


def main() -> int:
    raw = os.environ.get("SINGA_TPU_FLEET_SPEC")
    if not raw:
        raise SystemExit(
            "fleet_worker: SINGA_TPU_FLEET_SPEC is not set — this "
            "module is spawned by singa_tpu.fleet_proc.ProcReplica, "
            "not run by hand")
    spec = json.loads(raw)
    name = spec.get("name", "worker")

    # Platform pinning BEFORE any singa_tpu/jax import builds a
    # backend: the parent names the platform (tier-1 pins cpu); an
    # environment sitecustomize may have pointed jax elsewhere.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
        from jax.extend.backend import clear_backends

        clear_backends()

    from singa_tpu import device, resilience, serve, stats
    from singa_tpu import fleet_proc as wire
    from singa_tpu import trace as trace_mod

    if spec.get("export_cache"):
        device.set_export_cache(spec["export_cache"])
    if spec.get("buckets"):
        device.set_shape_buckets(**spec["buckets"])

    def arm_tracing(ship_capacity=2048, ring_capacity=None):
        """Worker tracer + span ship-back: completed spans carrying a
        trace context are drained (bounded per frame) onto REP/HB/BYE
        frames for the parent's merged timeline. Overflow of the
        bounded ship buffer drops oldest, counted — frames never grow
        unboundedly."""
        trace_mod.configure(enabled=True,
                            ship_capacity=int(ship_capacity),
                            ring_capacity=ring_capacity)

    tr_spec = spec.get("trace") or {}
    if tr_spec.get("enabled"):
        arm_tracing(tr_spec.get("ship_capacity", 2048),
                    tr_spec.get("ring_capacity"))

    factory = wire.resolve_factory(spec)
    t0 = time.perf_counter()
    model = factory(**(spec.get("factory_kwargs") or {}))
    _log(f"{name}: model built in {time.perf_counter() - t0:.2f}s "
         f"(platform {plat or 'default'})")

    injector = None
    if spec.get("injector"):
        ij = spec["injector"]
        injector = resilience.FaultInjector(
            seed=int(ij.get("seed", 0)),
            schedule=ij.get("schedule") or {},
            hang_s=float(ij.get("hang_s", 0.05)))
    metrics = None
    if spec.get("metrics_path"):
        metrics = trace_mod.MetricsLogger(spec["metrics_path"])
    engine = serve.ServingEngine(model, fault_injector=injector,
                                 metrics=metrics,
                                 **(spec.get("engine") or {}))
    engine.start()
    if spec.get("warm_decode"):
        # decode-tier AOT warmup at boot (and at every RESPAWN —
        # restart() reuses this spec): with the shared store prewarmed
        # this is deserialize-only, so a respawned replica re-enters
        # the decode rotation without paying a compile
        wd = spec["warm_decode"]
        t0 = time.perf_counter()
        n = engine.warm_decode(wd.get("prompt_lens") or (),
                               wd.get("max_new_tokens"),
                               samplers=wd.get("samplers") or ())
        _log(f"{name}: decode tier warmed ({n} executables, "
             f"{time.perf_counter() - t0:.2f}s)")

    import socket

    sock = socket.create_connection(
        ("127.0.0.1", int(spec["port"])), timeout=30.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    wlock = threading.Lock()
    tear_next = threading.Event()  # torn_frame chaos: corrupt next REP
    stop_ev = threading.Event()
    outbox_lock = threading.Lock()
    flush_lock = threading.Lock()  # serializes waiter vs drain flush
    outbox = []  # [(rid, ServeReply)] admitted, awaiting resolution

    def send(ftype, rid, payload, rep_frame=False):
        corrupt = rep_frame and tear_next.is_set()
        if corrupt:
            tear_next.clear()
        with wlock:
            sock.sendall(wire.encode_frame(ftype, rid, payload,
                                           corrupt=corrupt))

    def counters_payload():
        s = stats.cache_stats()
        d = s["decode"]
        out = {
            "terminal": serve.terminal_counters(),
            "poisoned": s["serve"]["poisoned"],
            "late": s["serve"]["late"],
            "export": {"hits": s["export"]["hits"],
                       "traces": s["export"]["traces"],
                       "misses": s["export"]["misses"]},
            # decode-session books (ISSUE 17): the worker side of the
            # fleet-wide 4-equation reconciliation — sessions ==
            # completed + failed + expired + shed, with migrated/
            # resumed tracking the sessions that crossed replicas
            "decode": {k: int(d.get(k, 0)) for k in (
                "sessions", "completed", "failed", "expired", "shed",
                "migrated", "resumed", "tokens_streamed", "prefills",
                "decode_steps", "slots", "slots_in_use")},
            "pid": os.getpid(),
        }
        if trace_mod.enabled():
            t = s["trace"]
            out["trace"] = {"spans": t["spans"],
                            "shipped": t["shipped"],
                            "ship_dropped": t["ship_dropped"]}
        return out

    def send_hb():
        snap = engine.health()
        snap["time"] = round(time.time(), 3)
        snap["name"] = name
        hb = counters_payload()
        hb["health"] = snap
        hb["retry_after_ms"] = engine._estimate_retry_after_ms(
            engine._depth)
        if trace_mod.enabled():
            # (wall, mono) pair: the parent's fallback clock-offset
            # estimate; completed trace-stamped spans piggyback here
            # so even a request-quiet worker keeps shipping. Both
            # keys exist ONLY while tracing is armed — a disabled
            # fleet's heartbeats are byte-identical to pre-trace.
            hb["clock"] = {"mono": time.perf_counter(),
                           "wall": time.time()}
            spans = trace_mod.drain_shipped(wire.SPANS_PER_HB)
            if spans:
                hb["spans"] = spans
        send(wire.HB, 0, json.dumps(hb, default=str).encode("utf-8"))

    def heartbeat_loop():
        interval = float(spec.get("heartbeat_interval_s", 0.25))
        while not stop_ev.wait(interval):
            try:
                send_hb()
            except OSError:
                return

    def flush_done(block_all: bool = False) -> None:
        """Send REP/ERR for every resolved future in the outbox;
        `block_all` waits every future out (the drain path — the
        reconciliation handshake must account for them all).
        `flush_lock` keeps the waiter thread and the drain path from
        double-sending one request's frame."""
        with flush_lock:
            while True:
                with outbox_lock:
                    items = list(outbox)
                if not items:
                    return
                progressed = False
                for rid, reply in items:
                    if not reply.done():
                        if block_all:
                            try:
                                reply.result(30.0)
                            except BaseException:
                                pass
                        else:
                            continue
                    try:
                        val = reply.result(0.0)
                        flags = 1 if reply.deadline_exceeded else 0
                        # piggyback trace spans ONLY under ship-buffer
                        # pressure (heartbeats are the steady-state
                        # carrier — span bytes here are request-path
                        # latency); an untraced run drains nothing and
                        # the flag bit stays 0 — byte-identical to the
                        # pre-trace REP layout
                        pending, cap = trace_mod.ship_backlog()
                        spans = (trace_mod.drain_shipped(
                            wire.SPANS_PER_REP)
                            if cap and pending >= cap // 2 else [])
                        if spans:
                            flags |= 2
                        payload = bytes([flags])
                        payload += wire.encode_tree(val)
                        if spans:
                            sb = json.dumps(spans, default=str).encode("utf-8")
                            payload += struct.pack(">I", len(sb)) + sb
                        send(wire.REP, rid, payload, rep_frame=True)
                    except BaseException as e:  # noqa: BLE001 — wire
                        send(wire.ERR, rid, json.dumps(
                            wire.encode_error(e)).encode("utf-8"))
                    with outbox_lock:
                        outbox.remove((rid, reply))
                    progressed = True
                if not block_all:
                    return
                if not progressed:
                    time.sleep(0.005)

    def waiter_loop():
        while not stop_ev.is_set():
            flush_done()
            time.sleep(0.001)

    # -- decode tier (ISSUE 17) -------------------------------------------
    # One streamer thread per admitted session: every generated token
    # rides a TOK frame as its fused step lands, and the terminal is
    # exactly ONE of REP (completed — the full [1, P+n] array, the
    # bit-identity surface), ERR (failed/expired), or MIGRATE (the
    # session left with the drain checkpoint; supersedes ERR — a
    # migrated session has no local terminal, it re-admits elsewhere).
    decode_threads = []

    def stream_decode(rid, reply):
        try:
            try:
                for tok in reply.tokens():
                    send(wire.TOK, rid, struct.pack(">i", int(tok)))
            except serve.ServeMigratedError as e:
                send(wire.MIGRATE, rid, wire.encode_tree(e.ckpt))
                return
            except BaseException as e:  # noqa: BLE001 — wire
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
                return
            val = reply.result(0.0)
            flags = 1 if reply.deadline_exceeded else 0
            send(wire.REP, rid, bytes([flags]) + wire.encode_tree(val),
                 rep_frame=True)
        except OSError:
            pass  # parent gone: its death sweep owns the accounting

    def admit_decode(rid, admit, tid, parent):
        """Shared DECODE/RESUME admission: sync ACK (exact engine
        error types on refusal, the REQ contract) then a streamer
        thread owns the session's frames."""
        if tid is not None and not trace_mod.enabled():
            arm_tracing()
        try:
            with trace_mod.context(tid, parent):
                reply = admit()
        except BaseException as e:  # noqa: BLE001 — wire
            send(wire.ERR, rid, json.dumps(
                wire.encode_error(e)).encode("utf-8"))
            return
        send(wire.ACK, rid,
             b"" if tid is None
             else struct.pack(">d", time.perf_counter()))
        t = threading.Thread(target=stream_decode, args=(rid, reply),
                             daemon=True)
        decode_threads.append(t)
        t.start()

    def handle_ctrl(rid, msg):
        op = msg.get("op")
        if op == "drain":
            return "drain", bool(msg.get("drain", True))
        if op == "counters":
            send(wire.CTRL_OK, rid,
                 json.dumps(counters_payload()).encode("utf-8"))
        elif op == "warm_decode":
            try:
                warmed = engine.warm_decode(
                    msg.get("prompt_lens") or (),
                    msg.get("max_new_tokens"),
                    samplers=msg.get("samplers") or ())
                send(wire.CTRL_OK, rid, json.dumps(
                    {"warmed": warmed}).encode("utf-8"))
            except BaseException as e:  # noqa: BLE001 — wire
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
        elif op == "hang_once":
            hang_s = float(msg.get("s", 0.05))
            orig = engine._chaos_attempt
            fired = []

            def hooked(group):
                if not fired:
                    fired.append(1)
                    engine._chaos_attempt = orig
                    time.sleep(hang_s)
                return orig(group)

            engine._chaos_attempt = hooked
        elif op == "torn_frame":
            tear_next.set()
        return None, None

    send(wire.HELLO, 0, json.dumps(
        {"token": spec.get("token"), "pid": os.getpid(),
         "name": name}).encode("utf-8"))
    # First heartbeat IMMEDIATELY: the router must never see a
    # just-started (or just-respawned) worker as stale for a whole
    # heartbeat interval — that window would eject every fresh boot.
    send_hb()
    threading.Thread(target=heartbeat_loop, daemon=True).start()
    threading.Thread(target=waiter_loop, daemon=True).start()

    reader = wire.FrameReader()
    sock.settimeout(0.2)
    drain_mode = None
    try:
        while drain_mode is None:
            try:
                chunk = sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                _log(f"{name}: socket error; exiting")
                return 1
            if not chunk:
                _log(f"{name}: parent closed the pipe; exiting")
                engine.stop(drain=False, drain_timeout_s=1.0)
                return 0
            for ftype, rid, payload in reader.feed(chunk):
                if ftype == wire.REQ:
                    dl, arrays, tid, parent = \
                        wire.decode_req_payload(payload)
                    if tid is not None and not trace_mod.enabled():
                        # parent enabled tracing after this worker
                        # spawned: a traced REQ arms it lazily
                        arm_tracing()
                    try:
                        with trace_mod.context(tid, parent):
                            reply = engine.submit(*arrays,
                                                  deadline_ms=dl)
                    except BaseException as e:  # noqa: BLE001
                        send(wire.ERR, rid, json.dumps(
                            wire.encode_error(e)).encode("utf-8"))
                        continue
                    # ACK strictly before the outbox registration:
                    # the waiter can then never put a REP on the wire
                    # ahead of its ACK. A TRACED request's ACK carries
                    # the worker perf_counter stamp (8 bytes) the
                    # parent's clock-offset estimate reads; an
                    # untraced ACK stays empty — zero added bytes.
                    send(wire.ACK, rid,
                         b"" if tid is None
                         else struct.pack(">d", time.perf_counter()))
                    with outbox_lock:
                        outbox.append((rid, reply))
                elif ftype == wire.DECODE:
                    d, tid, parent = wire.decode_decode_payload(payload)
                    dl = d.get("deadline_ms")
                    admit_decode(rid, lambda: engine.submit_decode(
                        np.asarray(d["prompt"], np.int32),
                        int(np.asarray(d["n_new"])),
                        temperature=float(np.asarray(d["temperature"])),
                        top_k=int(np.asarray(d["top_k"])),
                        seed=int(np.asarray(d["seed"])),
                        deadline_ms=(None if dl is None
                                     else float(np.asarray(dl)))),
                        tid, parent)
                elif ftype == wire.RESUME:
                    ckpt, tid, parent = \
                        wire.decode_resume_payload(payload)
                    admit_decode(rid,
                                 lambda: engine.resume_decode(ckpt),
                                 tid, parent)
                elif ftype == wire.WARM:
                    arrays = wire.decode_tree(payload)
                    try:
                        warmed = engine.warmup(*arrays)
                        send(wire.CTRL_OK, rid, json.dumps(
                            {"warmed": warmed}).encode("utf-8"))
                    except BaseException as e:  # noqa: BLE001
                        send(wire.ERR, rid, json.dumps(
                            wire.encode_error(e)).encode("utf-8"))
                elif ftype == wire.CTRL:
                    op, arg = handle_ctrl(
                        rid, json.loads(payload.decode("utf-8")))
                    if op == "drain":
                        drain_mode = ("drain" if arg else "fail")
                        break
    except wire.FrameCorruptError as e:
        _log(f"{name}: inbound frame corrupt ({e}); exiting loudly")
        engine.stop(drain=False, drain_timeout_s=1.0)
        return 1

    # Drain: stop the engine (failing or serving the queue per mode),
    # flush EVERY outstanding future as a frame, then ship the final
    # counters — the reconciliation handshake — and exit 0.
    _log(f"{name}: draining ({drain_mode})")
    # Live KV-slab migration (ISSUE 17): checkpoint every in-flight
    # decode session BEFORE the engine stop can fail it — the
    # streamer threads turn each ServeMigratedError into a MIGRATE
    # frame, and the parent re-places the session on another replica
    # with zero token loss. Runs in BOTH drain modes: migrating a
    # session is strictly better than failing it.
    try:
        exported = engine.export_decode_sessions()
        if exported:
            _log(f"{name}: exported {len(exported)} live decode "
                 "session(s) for migration")
    except Exception as e:  # noqa: BLE001 — drain must proceed
        _log(f"{name}: decode-session export failed ({e!r})")
    engine.stop(drain=(drain_mode == "drain"))
    for t in decode_threads:
        # every session's terminal frame (REP/ERR/MIGRATE) must be on
        # the wire before the BYE handshake ships the final counters
        t.join(10.0)
    flush_done(block_all=True)
    stop_ev.set()
    if metrics is not None:
        metrics.close()
    try:
        bye = counters_payload()
        spans = trace_mod.drain_shipped(wire.SPANS_PER_BYE)
        if spans:
            # last chance for still-buffered spans to reach the
            # parent's merged timeline before a clean exit
            bye["spans"] = spans
        send(wire.BYE, 0, json.dumps(bye, default=str).encode("utf-8"))
        sock.close()
    except OSError:
        pass
    _log(f"{name}: clean exit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
