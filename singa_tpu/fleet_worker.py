"""Fleet worker entrypoint (ISSUE 13, TCP modes ISSUE 18): one
serving replica in its own process, speaking the framed protocol of
`singa_tpu.fleet_proc` over a socket. Three launch shapes:

  * **spawn** (no CLI args): `ProcReplica(mode="spawn")` launched us
    with the replica spec in `SINGA_TPU_FLEET_SPEC` and a loopback
    port to dial — today's single-host behavior, unchanged. Socket
    EOF means the parent died: exit, no orphans.
  * **--connect HOST:PORT --token T [--name N]**: the multi-host
    launch recipe. The worker dials the parent's listener (or its
    ChaosProxy front door), authenticates with HELLO {token, fence,
    need_spec}, and receives WELCOME — which SHIPS the replica spec
    when the worker has none in its env (a remote host needs only
    this CLI plus the prewarmed export store). A lost connection is
    NOT death out here: the worker re-dials with seeded backoff
    inside the parent's advertised `reconnect_window_s`, echoing the
    generation fence from its WELCOME; the parent resumes the same
    generation (seqs reset per connection) or answers FENCED — the
    loud "you are superseded" verdict — and the worker exits.
  * **--listen HOST:PORT --token T [--name N]**: an already-running
    worker that a `ProcReplica(mode="connect")` parent dials. The
    worker accepts one parent at a time; the worker still speaks
    HELLO first. A FENCED verdict here resets the fence so the next
    parent (re)dial adopts the worker FRESH — a superseded fence is
    dead, never resurrected.

The worker

  1. forces the jax platform the parent named (`JAX_PLATFORMS` —
     tier-1 hermeticity: a CPU-pinned test suite must never have a
     worker wander onto an accelerator),
  2. arms the SHARED export-cache store (populate-once-start-N: with
     `tools/prewarm.py` run once, this boot — and every respawn after
     a SIGKILL — is deserialize-only, export hits >= 1, traces == 0),
  3. builds the model from the spec's deterministic factory
     ("module:callable", the `tools/prewarm.py --factory` idiom) and
     runs a `ServingEngine` over it,
  4. serves the framed request/reply protocol: REQ -> sync ACK
     (admission verdicts keep their exact single-engine error types)
     -> REP/ERR per request; HB heartbeats carry the engine `health()`
     snapshot plus the terminal/export counters the parent's
     reconciliation and deserialize-only pins read; a DRAIN control
     ships the final counters (BYE) — the end-of-run reconciliation
     handshake — before a clean exit 0.

Every frame out carries a per-connection monotonic sequence number
(wire v2) and every frame in is checked (`FrameReader(check_seq=
True)`): duplication or reordering on the path is a typed error, not
data. Sends go through the partial-write-hardened `send_frame` loop
under one lock — two threads can never interleave bytes mid-frame.

The worker never writes to stdout (the parent may be a bench stage
whose stdout is a JSON contract); logs go to stderr."""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import threading
import time

import numpy as np


def _log(msg: str) -> None:
    print(f"[fleet-worker {os.getpid()}] {msg}", file=sys.stderr,
          flush=True)


class _Fenced(RuntimeError):
    """The parent answered FENCED: this worker's generation (or its
    fresh-boot claim) is refused. Not retryable on the same fence."""


def _parse_addr(s: str):
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="python -m singa_tpu.fleet_worker",
        description="fleet serving worker (spawned by ProcReplica, "
                    "or launched on any host with --connect)")
    ap.add_argument("--connect", metavar="HOST:PORT", default=None,
                    help="dial a ProcReplica(mode='listen') parent")
    ap.add_argument("--listen", metavar="HOST:PORT", default=None,
                    help="accept a ProcReplica(mode='connect') parent")
    ap.add_argument("--token", default=None,
                    help="shared auth token (HELLO is refused "
                         "without it)")
    ap.add_argument("--name", default=None,
                    help="replica name for logs/heartbeats")
    args = ap.parse_args(argv)
    if args.connect and args.listen:
        ap.error("--connect and --listen are mutually exclusive")
    if (args.connect or args.listen) and not args.token:
        ap.error("--token is required with --connect/--listen")
    return args


def main(argv=None) -> int:
    args = _parse_args(argv)
    mode = ("connect" if args.connect
            else "listen" if args.listen else "spawn")
    raw = os.environ.get("SINGA_TPU_FLEET_SPEC")
    if mode == "spawn" and not raw:
        raise SystemExit(
            "fleet_worker: SINGA_TPU_FLEET_SPEC is not set — this "
            "module is spawned by singa_tpu.fleet_proc.ProcReplica; "
            "to run it by hand use --connect HOST:PORT --token ...")
    spec = json.loads(raw) if raw else None

    # Platform pinning BEFORE any singa_tpu/jax import builds a
    # backend: the parent names the platform (tier-1 pins cpu); an
    # environment sitecustomize may have pointed jax elsewhere.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
        from jax.extend.backend import clear_backends

        clear_backends()

    from singa_tpu import device, resilience, serve, stats
    from singa_tpu import fleet_proc as wire
    from singa_tpu import slo as slo_mod
    from singa_tpu import trace as trace_mod

    import socket

    token = args.token if args.token is not None \
        else (spec or {}).get("token")
    name = args.name or (spec or {}).get("name", "worker")
    tcp = mode != "spawn"

    # -- connection state: one link, many connection epochs ---------------
    # All sends funnel through `link_send` under ONE lock: the frame
    # gets this connection's next sequence number and goes out via the
    # partial-write-hardened `wire.send_frame` loop. A send failure
    # poisons the connection (bytes may be half out — it can never
    # carry another frame); in tcp mode the serve loop then runs the
    # re-adoption machinery instead of exiting.
    wlock = threading.Lock()
    link = {"sock": None, "tx_seq": 0}
    state = {"fence": None, "window_s": 10.0, "fenced_streak": 0}

    def link_attach(s, tx_seq=0):
        with wlock:
            link["sock"] = s
            link["tx_seq"] = tx_seq

    def link_detach(s=None):
        with wlock:
            if s is None or link["sock"] is s:
                link["sock"] = None

    def link_send(ftype, rid, payload, corrupt=False):
        with wlock:
            s = link["sock"]
            if s is None:
                raise OSError("link down (reconnecting)")
            frame = wire.encode_frame(ftype, rid, payload,
                                      corrupt=corrupt,
                                      seq=link["tx_seq"])
            try:
                wire.send_frame(s, frame, deadline_s=10.0)
            except OSError:
                link["sock"] = None
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                raise
            link["tx_seq"] += 1

    def handshake(conn, need_spec, deadline_s=30.0):
        """HELLO -> WELCOME/FENCED on a fresh connection. The worker
        speaks first; its HELLO is the connection's frame seq 0, so
        after a WELCOME the link attaches at tx_seq=1. Frames
        coalesced behind the WELCOME come back for serve-loop
        replay."""
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        rd = wire.FrameReader(check_seq=True)
        hello = {"token": token, "pid": os.getpid(), "name": name,
                 "fence": state["fence"], "need_spec": bool(need_spec)}
        wire.send_frame(conn, wire.encode_frame(
            wire.HELLO, 0, json.dumps(hello).encode("utf-8"), seq=0),
            deadline_s=min(10.0, deadline_s))
        conn.settimeout(0.2)
        deadline = time.perf_counter() + deadline_s
        welcome, stash = None, []
        while welcome is None:
            if time.perf_counter() > deadline:
                raise OSError(f"no WELCOME within {deadline_s:g}s")
            try:
                chunk = conn.recv(1 << 16)
            except socket.timeout:
                continue
            if not chunk:
                raise OSError("connection closed before WELCOME")
            for ftype, rid, payload in rd.feed(chunk):
                if ftype == wire.FENCED:
                    try:
                        reason = json.loads(
                            payload.decode("utf-8")).get("reason")
                    except Exception:
                        reason = "?"
                    raise _Fenced(str(reason))
                if ftype == wire.WELCOME and welcome is None:
                    welcome = json.loads(payload.decode("utf-8"))
                else:
                    stash.append((ftype, rid, payload))
        state["fence"] = welcome.get("fence")
        state["window_s"] = float(
            welcome.get("reconnect_window_s", state["window_s"]))
        state["fenced_streak"] = 0
        return welcome, rd, stash

    lsock = None
    if mode == "listen":
        lhost, lport = _parse_addr(args.listen)
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((lhost, lport))
        lsock.listen(1)
        lsock.settimeout(1.0)
        _log(f"{name}: listening on "
             f"{lsock.getsockname()[0]}:{lsock.getsockname()[1]}")

    def accept_parent():
        """listen mode: wait for a parent to dial and authenticate.
        A FENCED verdict resets the fence — the next adoption is
        FRESH by construction — and keeps waiting (bounded streak)."""
        while True:
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return None  # listener closed
            try:
                return (conn,) + handshake(
                    conn, need_spec=spec is None, deadline_s=15.0)
            except _Fenced as e:
                _log(f"{name}: FENCED ({e}); fence reset — next "
                     "adoption is fresh")
                state["fence"] = None
                state["fenced_streak"] += 1
                try:
                    conn.close()
                except OSError:
                    pass
                if state["fenced_streak"] >= 8:
                    _log(f"{name}: fenced {state['fenced_streak']}x "
                         "in a row; giving up")
                    return None
            except (OSError, wire.FrameCorruptError) as e:
                # a corrupt/reordered handshake frame (a chaotic
                # network CAN mangle the WELCOME) drops the dial, not
                # the worker — the parent redials
                _log(f"{name}: handshake failed ({e}); waiting")
                try:
                    conn.close()
                except OSError:
                    pass

    def redial():
        """connect mode: bounded seeded-backoff re-dial echoing the
        stored generation fence. FENCED => superseded => give up."""
        deadline = time.perf_counter() + state["window_s"] + 5.0
        attempt = 0
        while True:
            left = deadline - time.perf_counter()
            if left <= 0:
                _log(f"{name}: redial window "
                     f"({state['window_s']:g}s) exhausted")
                return None
            time.sleep(min(left, resilience.backoff_delay_s(
                attempt, 0.05, seed=os.getpid() & 0x7FFFFFFF,
                salt="redial")))
            attempt += 1
            try:
                s = socket.create_connection(dial_addr, timeout=5.0)
            except OSError:
                continue
            try:
                return (s,) + handshake(s, need_spec=False,
                                        deadline_s=10.0)
            except _Fenced as e:
                _log(f"{name}: reconnect FENCED ({e}); exiting")
                try:
                    s.close()
                except OSError:
                    pass
                return None
            except (OSError, wire.FrameCorruptError):
                # timeout, reset, or a WELCOME mangled in transit:
                # close and redial — only FENCED ends the attempt loop
                try:
                    s.close()
                except OSError:
                    pass

    # -- first connection (tcp) / spec resolution -------------------------
    stash0: list = []
    welcome = None
    if mode == "connect":
        dial_addr = _parse_addr(args.connect)
        # bounded retries: the first WELCOME can be mangled in transit
        # on a chaotic network just like any later one
        for boot_attempt in range(5):
            try:
                sock = socket.create_connection(dial_addr,
                                                timeout=30.0)
            except OSError as e:
                raise SystemExit(
                    f"fleet_worker: cannot dial parent at "
                    f"{dial_addr[0]}:{dial_addr[1]} ({e})")
            try:
                welcome, reader, stash0 = handshake(
                    sock, need_spec=spec is None)
                break
            except _Fenced as e:
                raise SystemExit(
                    f"fleet_worker: refused by parent (FENCED: {e})")
            except (OSError, wire.FrameCorruptError) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                if boot_attempt == 4:
                    raise SystemExit(
                        f"fleet_worker: handshake never completed "
                        f"({e})")
                _log(f"{name}: boot handshake failed ({e}); "
                     "redialing")
                time.sleep(0.1 * (boot_attempt + 1))
    elif mode == "listen":
        got = accept_parent()
        if got is None:
            raise SystemExit("fleet_worker: no parent adopted us")
        sock, welcome, reader, stash0 = got
    if tcp and spec is None:
        spec = welcome.get("spec")
        if spec is None:
            raise SystemExit(
                "fleet_worker: no spec in env and the parent's "
                "WELCOME shipped none")
    spec.setdefault("name", name)
    name = spec["name"]

    # -- engine boot (shared by all modes) --------------------------------
    if spec.get("export_cache"):
        device.set_export_cache(spec["export_cache"])
    if spec.get("buckets"):
        device.set_shape_buckets(**spec["buckets"])
    if spec.get("quant"):
        # int8 inference (ISSUE 19): armed BEFORE the model/engine
        # build so the slab, the warmed ladder, and the AOT keys all
        # agree — every replica of a fleet must share the mode or
        # MIGRATE frames would cross quant forms (import_slab_rows
        # refuses loudly and the session demotes to replay)
        device.set_inference_quant(spec["quant"])

    def arm_tracing(ship_capacity=2048, ring_capacity=None):
        """Worker tracer + span ship-back: completed spans carrying a
        trace context are drained (bounded per frame) onto REP/HB/BYE
        frames for the parent's merged timeline. Overflow of the
        bounded ship buffer drops oldest, counted — frames never grow
        unboundedly."""
        trace_mod.configure(enabled=True,
                            ship_capacity=int(ship_capacity),
                            ring_capacity=ring_capacity)

    tr_spec = spec.get("trace") or {}
    if tr_spec.get("enabled"):
        arm_tracing(tr_spec.get("ship_capacity", 2048),
                    tr_spec.get("ring_capacity"))
    slo_spec = spec.get("slo") or {}
    if slo_spec.get("enabled"):
        # ISSUE 20: arm the worker's local SLO sketches from the
        # router's spec so the whole fleet samples under ONE spec;
        # workers never write alerts (the router holds the merged
        # view and the alerting state) — alerts_path stays None here
        slo_mod.configure(**dict(slo_spec, alerts_path=None))

    factory = wire.resolve_factory(spec)
    t0 = time.perf_counter()
    model = factory(**(spec.get("factory_kwargs") or {}))
    _log(f"{name}: model built in {time.perf_counter() - t0:.2f}s "
         f"(platform {plat or 'default'}, mode {mode})")

    injector = None
    if spec.get("injector"):
        ij = spec["injector"]
        injector = resilience.FaultInjector(
            seed=int(ij.get("seed", 0)),
            schedule=ij.get("schedule") or {},
            hang_s=float(ij.get("hang_s", 0.05)))
    metrics = None
    if spec.get("metrics_path"):
        metrics = trace_mod.MetricsLogger(spec["metrics_path"])
    engine = serve.ServingEngine(model, fault_injector=injector,
                                 metrics=metrics,
                                 **(spec.get("engine") or {}))
    engine.start()
    if spec.get("warm_decode"):
        # decode-tier AOT warmup at boot (and at every RESPAWN —
        # restart() reuses this spec): with the shared store prewarmed
        # this is deserialize-only, so a respawned replica re-enters
        # the decode rotation without paying a compile
        wd = spec["warm_decode"]
        t0 = time.perf_counter()
        n = engine.warm_decode(wd.get("prompt_lens") or (),
                               wd.get("max_new_tokens"),
                               samplers=wd.get("samplers") or ())
        _log(f"{name}: decode tier warmed ({n} executables, "
             f"{time.perf_counter() - t0:.2f}s)")

    if mode == "spawn":
        sock = socket.create_connection(
            ("127.0.0.1", int(spec["port"])), timeout=30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        reader = wire.FrameReader(check_seq=True)
        link_attach(sock, tx_seq=0)
    else:
        link_attach(sock, tx_seq=1)  # HELLO was this link's seq 0

    tear_next = threading.Event()  # torn_frame chaos: corrupt next REP
    stop_ev = threading.Event()
    outbox_lock = threading.Lock()
    flush_lock = threading.Lock()  # serializes waiter vs drain flush
    outbox = []  # [(rid, ServeReply)] admitted, awaiting resolution

    def send(ftype, rid, payload, rep_frame=False):
        corrupt = rep_frame and tear_next.is_set()
        if corrupt:
            tear_next.clear()
        link_send(ftype, rid, payload, corrupt=corrupt)

    def counters_payload():
        s = stats.cache_stats()
        d = s["decode"]
        out = {
            "terminal": serve.terminal_counters(),
            "poisoned": s["serve"]["poisoned"],
            "late": s["serve"]["late"],
            "export": {"hits": s["export"]["hits"],
                       "traces": s["export"]["traces"],
                       "misses": s["export"]["misses"]},
            # decode-session books (ISSUE 17): the worker side of the
            # fleet-wide 4-equation reconciliation — sessions ==
            # completed + failed + expired + shed, with migrated/
            # resumed tracking the sessions that crossed replicas
            "decode": {k: int(d.get(k, 0)) for k in (
                "sessions", "completed", "failed", "expired", "shed",
                "migrated", "resumed", "tokens_streamed", "prefills",
                "decode_steps", "slots", "slots_in_use")},
            "pid": os.getpid(),
        }
        if trace_mod.enabled():
            t = s["trace"]
            out["trace"] = {"spans": t["spans"],
                            "shipped": t["shipped"],
                            "ship_dropped": t["ship_dropped"]}
        # ISSUE 20: cumulative sketch payload — the key exists ONLY
        # while the SLO engine is armed (byte-absence, PR 15
        # discipline); cumulative-replace makes ingest idempotent
        # under heartbeat loss, duplication, and reconnect
        s_payload = slo_mod.wire_payload()
        if s_payload is not None:
            out["slo"] = s_payload
        return out

    def send_hb():
        snap = engine.health()
        snap["time"] = round(time.time(), 3)
        snap["name"] = name
        hb = counters_payload()
        hb["health"] = snap
        hb["retry_after_ms"] = engine._estimate_retry_after_ms(
            engine._depth)
        if trace_mod.enabled():
            # (wall, mono) pair: the parent's fallback clock-offset
            # estimate; completed trace-stamped spans piggyback here
            # so even a request-quiet worker keeps shipping. Both
            # keys exist ONLY while tracing is armed — a disabled
            # fleet's heartbeats are byte-identical to pre-trace.
            hb["clock"] = {"mono": time.perf_counter(),
                           "wall": time.time()}
            spans = trace_mod.drain_shipped(wire.SPANS_PER_HB)
            if spans:
                hb["spans"] = spans
        send(wire.HB, 0, json.dumps(hb, default=str).encode("utf-8"))

    def heartbeat_loop():
        interval = float(spec.get("heartbeat_interval_s", 0.25))
        while not stop_ev.wait(interval):
            try:
                send_hb()
            except OSError:
                if not tcp:
                    return
                # tcp: link down mid-reconnect — keep ticking; the
                # first beat after re-adoption lands on the new
                # connection (a resumed worker must re-enter the
                # rotation fresh, not stale)

    def flush_done(block_all: bool = False) -> None:
        """Send REP/ERR for every resolved future in the outbox;
        `block_all` waits every future out (the drain path — the
        reconciliation handshake must account for them all).
        `flush_lock` keeps the waiter thread and the drain path from
        double-sending one request's frame. A send failure (link
        down) leaves the item IN the outbox: it is resent on the next
        connection, where the parent — which swept the rid into
        failover when the connection died — drops it by rid. Never
        lost, never double-delivered."""
        with flush_lock:
            while True:
                with outbox_lock:
                    items = list(outbox)
                if not items:
                    return
                progressed = False
                for rid, reply in items:
                    if not reply.done():
                        if block_all:
                            try:
                                reply.result(30.0)
                            except BaseException:
                                pass
                        else:
                            continue
                    try:
                        val = reply.result(0.0)
                        flags = 1 if reply.deadline_exceeded else 0
                        # piggyback trace spans ONLY under ship-buffer
                        # pressure (heartbeats are the steady-state
                        # carrier — span bytes here are request-path
                        # latency); an untraced run drains nothing and
                        # the flag bit stays 0 — byte-identical to the
                        # pre-trace REP layout
                        pending, cap = trace_mod.ship_backlog()
                        spans = (trace_mod.drain_shipped(
                            wire.SPANS_PER_REP)
                            if cap and pending >= cap // 2 else [])
                        if spans:
                            flags |= 2
                        payload = bytes([flags])
                        payload += wire.encode_tree(val)
                        if spans:
                            sb = json.dumps(spans, default=str).encode("utf-8")
                            payload += struct.pack(">I", len(sb)) + sb
                        send(wire.REP, rid, payload, rep_frame=True)
                    except OSError:
                        raise
                    except BaseException as e:  # noqa: BLE001 — wire
                        send(wire.ERR, rid, json.dumps(
                            wire.encode_error(e)).encode("utf-8"))
                    with outbox_lock:
                        outbox.remove((rid, reply))
                    progressed = True
                if not block_all:
                    return
                if not progressed:
                    time.sleep(0.005)

    def waiter_loop():
        while not stop_ev.is_set():
            try:
                flush_done()
            except OSError:
                if not tcp:
                    return
            time.sleep(0.001)

    # -- decode tier (ISSUE 17) -------------------------------------------
    # One streamer thread per admitted session: every generated token
    # rides a TOK frame as its fused step lands, and the terminal is
    # exactly ONE of REP (completed — the full [1, P+n] array, the
    # bit-identity surface), ERR (failed/expired), or MIGRATE (the
    # session left with the drain checkpoint; supersedes ERR — a
    # migrated session has no local terminal, it re-admits elsewhere).
    decode_threads = []

    def stream_decode(rid, reply):
        try:
            try:
                for tok in reply.tokens():
                    send(wire.TOK, rid, struct.pack(">i", int(tok)))
            except serve.ServeMigratedError as e:
                send(wire.MIGRATE, rid, wire.encode_tree(e.ckpt))
                return
            except BaseException as e:  # noqa: BLE001 — wire
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
                return
            val = reply.result(0.0)
            flags = 1 if reply.deadline_exceeded else 0
            send(wire.REP, rid, bytes([flags]) + wire.encode_tree(val),
                 rep_frame=True)
        except OSError:
            pass  # connection gone: the parent swept this session
            # into failover (or its death sweep owns the books); a
            # late terminal on a later connection would be dropped
            # by rid anyway

    def admit_decode(rid, admit, tid, parent):
        """Shared DECODE/RESUME admission: sync ACK (exact engine
        error types on refusal, the REQ contract) then a streamer
        thread owns the session's frames."""
        if tid is not None and not trace_mod.enabled():
            arm_tracing()
        try:
            with trace_mod.context(tid, parent):
                reply = admit()
        except BaseException as e:  # noqa: BLE001 — wire
            send(wire.ERR, rid, json.dumps(
                wire.encode_error(e)).encode("utf-8"))
            return
        send(wire.ACK, rid,
             b"" if tid is None
             else struct.pack(">d", time.perf_counter()))
        t = threading.Thread(target=stream_decode, args=(rid, reply),
                             daemon=True)
        decode_threads.append(t)
        t.start()

    def handle_ctrl(rid, msg):
        op = msg.get("op")
        if op == "drain":
            return "drain", bool(msg.get("drain", True))
        if op == "counters":
            send(wire.CTRL_OK, rid,
                 json.dumps(counters_payload()).encode("utf-8"))
        elif op == "warm_decode":
            try:
                warmed = engine.warm_decode(
                    msg.get("prompt_lens") or (),
                    msg.get("max_new_tokens"),
                    samplers=msg.get("samplers") or ())
                send(wire.CTRL_OK, rid, json.dumps(
                    {"warmed": warmed}).encode("utf-8"))
            except BaseException as e:  # noqa: BLE001 — wire
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
        elif op == "hang_once":
            hang_s = float(msg.get("s", 0.05))
            orig = engine._chaos_attempt
            fired = []

            def hooked(group):
                if not fired:
                    fired.append(1)
                    engine._chaos_attempt = orig
                    time.sleep(hang_s)
                return orig(group)

            engine._chaos_attempt = hooked
        elif op == "torn_frame":
            tear_next.set()
        return None, None

    def dispatch(ftype, rid, payload):
        """One inbound frame => engine action. Returns the drain mode
        when a DRAIN control arrives, else None."""
        if ftype == wire.REQ:
            dl, arrays, tid, parent = \
                wire.decode_req_payload(payload)
            if tid is not None and not trace_mod.enabled():
                # parent enabled tracing after this worker
                # spawned: a traced REQ arms it lazily
                arm_tracing()
            try:
                with trace_mod.context(tid, parent):
                    reply = engine.submit(*arrays, deadline_ms=dl)
            except BaseException as e:  # noqa: BLE001
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
                return None
            # ACK strictly before the outbox registration:
            # the waiter can then never put a REP on the wire
            # ahead of its ACK. A TRACED request's ACK carries
            # the worker perf_counter stamp (8 bytes) the
            # parent's clock-offset estimate reads; an
            # untraced ACK stays empty — zero added bytes.
            send(wire.ACK, rid,
                 b"" if tid is None
                 else struct.pack(">d", time.perf_counter()))
            with outbox_lock:
                outbox.append((rid, reply))
        elif ftype == wire.DECODE:
            d, tid, parent = wire.decode_decode_payload(payload)
            dl = d.get("deadline_ms")
            admit_decode(rid, lambda: engine.submit_decode(
                np.asarray(d["prompt"], np.int32),
                int(np.asarray(d["n_new"])),
                temperature=float(np.asarray(d["temperature"])),
                top_k=int(np.asarray(d["top_k"])),
                seed=int(np.asarray(d["seed"])),
                deadline_ms=(None if dl is None
                             else float(np.asarray(dl)))),
                tid, parent)
        elif ftype == wire.RESUME:
            ckpt, tid, parent = \
                wire.decode_resume_payload(payload)
            admit_decode(rid,
                         lambda: engine.resume_decode(ckpt),
                         tid, parent)
        elif ftype == wire.WARM:
            arrays = wire.decode_tree(payload)
            try:
                warmed = engine.warmup(*arrays)
                send(wire.CTRL_OK, rid, json.dumps(
                    {"warmed": warmed}).encode("utf-8"))
            except BaseException as e:  # noqa: BLE001
                send(wire.ERR, rid, json.dumps(
                    wire.encode_error(e)).encode("utf-8"))
        elif ftype == wire.CTRL:
            op, arg = handle_ctrl(
                rid, json.loads(payload.decode("utf-8")))
            if op == "drain":
                return "drain" if arg else "fail"
        elif ftype == wire.FENCED:
            # mid-stream fence verdict: this connection (and in
            # connect mode this worker) is superseded
            try:
                reason = json.loads(
                    payload.decode("utf-8")).get("reason")
            except Exception:
                reason = "?"
            raise _Fenced(str(reason))
        return None

    if mode == "spawn":
        send(wire.HELLO, 0, json.dumps(
            {"token": token, "pid": os.getpid(),
             "name": name}).encode("utf-8"))
    # First heartbeat IMMEDIATELY: the router must never see a
    # just-started (or just-respawned) worker as stale for a whole
    # heartbeat interval — that window would eject every fresh boot.
    send_hb()
    threading.Thread(target=heartbeat_loop, daemon=True).start()
    threading.Thread(target=waiter_loop, daemon=True).start()

    # -- serve loop: one iteration per connection epoch -------------------
    drain_mode = None
    while drain_mode is None:
        sock.settimeout(0.2)
        lost = False
        try:
            for ftype, rid, payload in stash0:
                try:
                    drain_mode = dispatch(ftype, rid, payload) \
                        or drain_mode
                except OSError:
                    lost = True
                    break
                if drain_mode is not None:
                    break
            stash0 = []
            while drain_mode is None and not lost:
                try:
                    chunk = sock.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    if not tcp:
                        _log(f"{name}: socket error; exiting")
                        engine.stop(drain=False, drain_timeout_s=1.0)
                        return 1
                    lost = True
                    break
                if not chunk:
                    if not tcp:
                        _log(f"{name}: parent closed the pipe; "
                             "exiting")
                        engine.stop(drain=False, drain_timeout_s=1.0)
                        return 0
                    lost = True
                    break
                for ftype, rid, payload in reader.feed(chunk):
                    try:
                        drain_mode = dispatch(ftype, rid, payload) \
                            or drain_mode
                    except OSError:
                        lost = True
                        break
                    if drain_mode is not None:
                        break
        except wire.FrameCorruptError as e:
            if not tcp:
                _log(f"{name}: inbound frame corrupt ({e}); exiting "
                     "loudly")
                engine.stop(drain=False, drain_timeout_s=1.0)
                return 1
            # tcp: the CONNECTION is untrustworthy, the generation is
            # not — tear it down and re-handshake (fresh seqs both
            # directions)
            _log(f"{name}: inbound frame corrupt ({e}); "
                 "re-handshaking")
            lost = True
        except _Fenced as e:
            _log(f"{name}: fenced mid-stream ({e})")
            if mode == "connect":
                engine.stop(drain=False, drain_timeout_s=1.0)
                return 1
            state["fence"] = None  # listen: next adoption is fresh
            lost = True
        if drain_mode is not None or not lost:
            continue
        # -- connection lost (tcp): bounded re-adoption -------------------
        link_detach(sock)
        try:
            sock.close()
        except OSError:
            pass
        _log(f"{name}: connection lost; "
             + ("re-dialing parent" if mode == "connect"
                else "awaiting re-adoption"))
        got = redial() if mode == "connect" else accept_parent()
        if got is None:
            _log(f"{name}: no parent re-adopted us; exiting")
            engine.stop(drain=False, drain_timeout_s=1.0)
            return 1
        sock, welcome, reader, stash0 = got
        link_attach(sock, tx_seq=1)
        _log(f"{name}: "
             + (f"resumed generation (fence {state['fence']})"
                if welcome.get("resumed")
                else f"re-adopted fresh (fence {state['fence']})"))
        try:
            send_hb()  # immediately: never resume into staleness
        except OSError:
            pass

    # Drain: stop the engine (failing or serving the queue per mode),
    # flush EVERY outstanding future as a frame, then ship the final
    # counters — the reconciliation handshake — and exit 0.
    _log(f"{name}: draining ({drain_mode})")
    # Live KV-slab migration (ISSUE 17): checkpoint every in-flight
    # decode session BEFORE the engine stop can fail it — the
    # streamer threads turn each ServeMigratedError into a MIGRATE
    # frame, and the parent re-places the session on another replica
    # with zero token loss. Runs in BOTH drain modes: migrating a
    # session is strictly better than failing it.
    try:
        exported = engine.export_decode_sessions()
        if exported:
            _log(f"{name}: exported {len(exported)} live decode "
                 "session(s) for migration")
    except Exception as e:  # noqa: BLE001 — drain must proceed
        _log(f"{name}: decode-session export failed ({e!r})")
    engine.stop(drain=(drain_mode == "drain"))
    for t in decode_threads:
        # every session's terminal frame (REP/ERR/MIGRATE) must be on
        # the wire before the BYE handshake ships the final counters
        t.join(10.0)
    try:
        flush_done(block_all=True)
    except OSError:
        pass  # parent gone mid-drain: its death sweep owns the books
    stop_ev.set()
    if metrics is not None:
        metrics.close()
    try:
        bye = counters_payload()
        spans = trace_mod.drain_shipped(wire.SPANS_PER_BYE)
        if spans:
            # last chance for still-buffered spans to reach the
            # parent's merged timeline before a clean exit
            bye["spans"] = spans
        send(wire.BYE, 0, json.dumps(bye, default=str).encode("utf-8"))
        sock.close()
    except OSError:
        pass
    if lsock is not None:
        try:
            lsock.close()
        except OSError:
            pass
    _log(f"{name}: clean exit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
